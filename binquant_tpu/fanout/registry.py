"""Subscription registry + packed-bitset plane compiler (ISSUE 14).

The fan-out plane's data model: a :class:`Subscription` is one user's
standing filter — symbols × strategies × regimes × a minimum signal
strength — and the :class:`SubscriptionRegistry` compiles the whole user
population into dense uint32 bitset planes the device match kernel
(:mod:`binquant_tpu.fanout.kernel`) joins against a tick's fired slots in
ONE dispatch:

* ``sym_plane``    — ``(S, U32)``: bit ``u`` of word column set when user
  ``u`` subscribed to the symbol occupying engine row ``s`` explicitly;
* ``strat_plane``  — ``(N_strategies, U32)``: per-strategy user bits, row
  order = ``engine.step.STRATEGY_ORDER``;
* ``regime_plane`` — ``(len(MarketRegimeCode) + 1, U32)``: per-regime user
  bits; the extra trailing row is the *invalid-context* bucket (a tick
  whose market context has not stabilized matches only regime-wildcard
  subscribers);
* ``any_masks``    — ``(3, U32)``: the wildcard words (symbols=None /
  strategies=None / regimes=None — "all"), OR-ed into the corresponding
  plane gather at match time so a wildcard never pays a per-row fill;
* ``floors``       — ``(U,)`` f32 per-slot minimum strength (matched
  against ``|score|``; unoccupied slots carry ``+inf``).

``U32 = capacity // 32`` and ``U = capacity``; user slots pack LSB-first
into words (slot ``u`` → word ``u >> 5``, bit ``u & 31``), the exact
layout ``np.packbits(..., bitorder="little")`` produces, so the host
decodes device words with one ``np.unpackbits`` call.

Churn (add / update / remove) flips ONE bit column host-side and records
the touched ``(plane, row, word)`` CELLS dirty (ISSUE 20); the device
copy resynchronizes lazily at the next match via one jit'd
``apply_subscription_deltas`` dispatch of one-word scatters
(``kind="incremental"`` in ``bqt_fanout_recompiles_total``) — cost is
O(cells touched), independent of the resident population, so churn never
triggers a bulk rebuild. The tick step is never retraced, and the match
kernel itself only retraces when the slot capacity doubles
(``kind="full"``). :meth:`SubscriptionRegistry.compact` folds
tombstoned (freed) slots back into a dense block when fragmentation
crosses the plane's threshold. Symbol subscriptions are stored by NAME
and re-resolve against the engine's
:class:`~binquant_tpu.engine.buffer.SymbolRegistry` whenever its
``version`` moves (listing churn re-homes rows).

Snapshot-warm boot: :meth:`SubscriptionRegistry.export_columns` emits a
slot-ordered columnar image of the subscription index (uid/criteria
blobs + counts) that :meth:`restore_columns` adopts wholesale — restored
records materialize LAZILY on first touch through :class:`_RecordMap`,
so a million-user restore costs array loads + two dict builds, not a
million dataclass constructions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator

import numpy as np

from binquant_tpu.engine.step import STRATEGY_ORDER
from binquant_tpu.enums import MarketRegimeCode

# index into regime_plane for a tick without a valid market context
REGIME_ROWS = len(MarketRegimeCode) + 1
INVALID_REGIME_ROW = len(MarketRegimeCode)

_STRAT_IDX: dict[str, int] = {s: i for i, s in enumerate(STRATEGY_ORDER)}

# any_masks rows
ANY_SYM, ANY_STRAT, ANY_REGIME = 0, 1, 2

# delta-cell plane ids: a dirty cell is (plane_id, row, word) — the unit
# the jit'd apply_subscription_deltas scatter patches on device
P_SYM, P_STRAT, P_REGIME, P_ANY = 0, 1, 2, 3


@dataclass(frozen=True)
class Subscription:
    """One user's standing signal filter. ``None`` criteria mean "all"."""

    user_id: str
    symbols: frozenset[str] | None = None
    strategies: frozenset[str] | None = None
    regimes: frozenset[int] | None = None
    min_strength: float = 0.0

    def __post_init__(self) -> None:
        # the floor is quantized to f32 AT THE MODEL BOUNDARY: the device
        # planes store f32, and an unquantized f64 here would let oracle
        # and kernel disagree on scores inside the rounding gap (e.g.
        # floor 0.1: f32(0.1)=0.100000001 matches a score of 0.099999999
        # on device but not in f64)
        object.__setattr__(
            self, "min_strength", float(np.float32(self.min_strength))
        )
        if self.strategies is not None:
            unknown = set(self.strategies) - set(STRATEGY_ORDER)
            if unknown:
                raise ValueError(
                    f"unknown strategies {sorted(unknown)}; valid: "
                    f"{list(STRATEGY_ORDER)}"
                )
        if self.regimes is not None:
            bad = [r for r in self.regimes if not 0 <= int(r) < len(MarketRegimeCode)]
            if bad:
                raise ValueError(
                    f"regime codes {bad} outside MarketRegimeCode range"
                )

    def matches(
        self, strategy: str, symbol: str, score: float,
        regime: int | None,
    ) -> bool:
        """The Python-oracle predicate the device kernel must agree with
        bit-for-bit. ``regime=None`` is the invalid-context tick."""
        if self.strategies is not None and strategy not in self.strategies:
            return False
        if self.symbols is not None and symbol not in self.symbols:
            return False
        if self.regimes is not None and (
            regime is None or int(regime) not in {int(r) for r in self.regimes}
        ):
            return False
        # compare in f32, exactly as the kernel does (score is cast f32
        # on the way to the device; min_strength is f32-quantized above)
        return bool(
            np.abs(np.float32(score)) >= np.float32(self.min_strength)
        )


@dataclass
class _SlotRecord:
    sub: Subscription
    slot: int
    # engine rows the symbol set resolved to at the last row refresh
    rows: list[int] = field(default_factory=list)


def _norm_symbols(symbols: Iterable[str] | None) -> frozenset[str] | None:
    if symbols is None:
        return None
    return frozenset(s.strip().upper() for s in symbols)


def _fast_sub(
    user_id: str,
    symbols: frozenset[str] | None,
    strategies: frozenset[str] | None,
    regimes: frozenset[int] | None,
    min_strength: float,
) -> Subscription:
    """Rebuild a Subscription from archived columns WITHOUT
    ``__post_init__``: every field was validated and f32-quantized when
    originally added, so re-running the checks would only burn the
    warm-boot budget (measured ~4 s for 1M eager constructions)."""
    sub = Subscription.__new__(Subscription)
    d = sub.__dict__
    d["user_id"] = user_id
    d["symbols"] = symbols
    d["strategies"] = strategies
    d["regimes"] = regimes
    d["min_strength"] = min_strength
    return sub


class _ColumnarBase:
    """Decoded snapshot columns + the per-user lazy record factory.

    Holds the slot-ordered arrays :meth:`SubscriptionRegistry
    .export_columns` archived — uids, slots, per-criterion counts (−1 =
    wildcard) with flattened name/code blobs, resolved symbol rows — and
    a reference to the registry's live ``floors`` array (a slot's floor
    only mutates through ``_set_bits`` on a record that is then live, so
    reading it at materialization time is always current)."""

    __slots__ = (
        "uids", "slots", "floors",
        "sym_counts", "sym_names", "sym_off",
        "strat_counts", "strat_names", "strat_off",
        "reg_counts", "reg_flat", "reg_off",
        "row_counts", "rows_flat", "row_off",
    )

    @staticmethod
    def _split(blob: np.ndarray) -> list[str]:
        if blob.size == 0:
            return []
        return blob.tobytes().decode("utf-8").split("\n")

    @staticmethod
    def _offsets(counts: np.ndarray) -> np.ndarray:
        return np.concatenate(
            ([0], np.cumsum(np.maximum(counts, 0), dtype=np.int64))
        )

    def __init__(self, arrays: dict, floors: np.ndarray) -> None:
        self.uids = self._split(arrays["uid_blob"])
        self.slots = np.asarray(arrays["slots"], np.int64)
        self.floors = floors
        self.sym_counts = np.asarray(arrays["sym_counts"], np.int64)
        self.sym_names = self._split(arrays["sym_blob"])
        self.sym_off = self._offsets(self.sym_counts)
        self.strat_counts = np.asarray(arrays["strat_counts"], np.int64)
        self.strat_names = self._split(arrays["strat_blob"])
        self.strat_off = self._offsets(self.strat_counts)
        self.reg_counts = np.asarray(arrays["reg_counts"], np.int64)
        self.reg_flat = np.asarray(arrays["reg_flat"], np.int64)
        self.reg_off = self._offsets(self.reg_counts)
        self.row_counts = np.asarray(arrays["row_counts"], np.int64)
        self.rows_flat = np.asarray(arrays["rows_flat"], np.int64)
        self.row_off = self._offsets(self.row_counts)

    def row(self, k: int) -> tuple:
        """Column slice ``k`` as an export tuple — no object builds."""
        syms = (
            self.sym_names[self.sym_off[k]: self.sym_off[k + 1]]
            if self.sym_counts[k] >= 0 else None
        )
        strats = (
            self.strat_names[self.strat_off[k]: self.strat_off[k + 1]]
            if self.strat_counts[k] >= 0 else None
        )
        regs = (
            self.reg_flat[self.reg_off[k]: self.reg_off[k + 1]].tolist()
            if self.reg_counts[k] >= 0 else None
        )
        rows = self.rows_flat[self.row_off[k]: self.row_off[k + 1]].tolist()
        return (self.uids[k], int(self.slots[k]), syms, strats, regs, rows)

    def record(self, k: int) -> _SlotRecord:
        uid, slot, syms, strats, regs, rows = self.row(k)
        sub = _fast_sub(
            uid,
            frozenset(syms) if syms is not None else None,
            frozenset(strats) if strats is not None else None,
            frozenset(int(r) for r in regs) if regs is not None else None,
            float(self.floors[slot]),
        )
        return _SlotRecord(sub=sub, slot=slot, rows=rows)


class _RecordMap:
    """``user_id → _SlotRecord`` mapping with an optional columnar base.

    Without a base it is a plain dict. After :meth:`SubscriptionRegistry
    .restore_columns` attaches one, records materialize on first touch
    (get/pop/setitem), keeping warm boot O(archive load); bulk consumers
    (``values``/``items`` — the match oracle, compaction, tests)
    materialize everything and are deliberately the slow path."""

    __slots__ = ("_live", "_base", "_base_idx")

    def __init__(self) -> None:
        self._live: dict[str, _SlotRecord] = {}
        self._base: _ColumnarBase | None = None
        # uid → column index for records NOT yet materialized (keys are
        # always disjoint from _live)
        self._base_idx: dict[str, int] = {}

    def attach_base(self, base: _ColumnarBase) -> None:
        self._base = base
        self._base_idx = {u: k for k, u in enumerate(base.uids)}

    @property
    def lazy_count(self) -> int:
        return len(self._base_idx)

    def _materialize(self, uid: str) -> _SlotRecord:
        k = self._base_idx.pop(uid)
        rec = self._base.record(k)
        self._live[uid] = rec
        return rec

    def __len__(self) -> int:
        return len(self._live) + len(self._base_idx)

    def __contains__(self, uid: str) -> bool:
        return uid in self._live or uid in self._base_idx

    def __iter__(self) -> Iterator[str]:
        yield from self._live
        yield from list(self._base_idx)

    def get(self, uid: str, default=None):
        rec = self._live.get(uid)
        if rec is not None:
            return rec
        if uid in self._base_idx:
            return self._materialize(uid)
        return default

    def __getitem__(self, uid: str) -> _SlotRecord:
        rec = self.get(uid)
        if rec is None:
            raise KeyError(uid)
        return rec

    def __setitem__(self, uid: str, rec: _SlotRecord) -> None:
        self._base_idx.pop(uid, None)
        self._live[uid] = rec

    def pop(self, uid: str, default=None):
        if uid in self._base_idx:
            self._materialize(uid)
        return self._live.pop(uid, default)

    def values(self):
        for uid in list(self._base_idx):
            self._materialize(uid)
        return self._live.values()

    def items(self):
        for uid in list(self._base_idx):
            self._materialize(uid)
        return self._live.items()

    def export_rows(self) -> Iterator[tuple]:
        """Yield ``(uid, slot, symbols, strategies, regimes, rows)`` for
        every record — live ones from their objects, lazy ones straight
        from the columns (no materialization; criteria lists sorted for a
        deterministic archive)."""
        for rec in self._live.values():
            sub = rec.sub
            yield (
                sub.user_id,
                rec.slot,
                sorted(sub.symbols) if sub.symbols is not None else None,
                sorted(sub.strategies)
                if sub.strategies is not None else None,
                sorted(int(r) for r in sub.regimes)
                if sub.regimes is not None else None,
                list(rec.rows),
            )
        if self._base is not None:
            for uid in list(self._base_idx):
                yield self._base.row(self._base_idx[uid])


class SubscriptionRegistry:
    """Host-authoritative subscription store + bitset plane compiler.

    ``capacity`` is the user-slot bound (rounded up to a multiple of 32);
    adding past it doubles the planes (a deliberate, counted match-kernel
    retrace — the only one). Every mutation updates the numpy planes in
    place and records the touched (plane, row, word) cells dirty; the
    device sync policy lives in
    :class:`binquant_tpu.fanout.plane.FanoutPlane`.
    """

    def __init__(self, symbol_capacity: int, capacity: int = 1024) -> None:
        self.symbol_capacity = int(symbol_capacity)
        cap = max(int(capacity), 32)
        self.capacity = (cap + 31) & ~31
        self._initial_capacity = self.capacity
        self._records = _RecordMap()
        # user_ids with EXPLICIT symbol criteria — the only records a
        # symbol-row refresh must re-resolve (keeps listing churn
        # O(explicit subs), not O(population))
        self._explicit: set[str] = set()
        self._slot_user: dict[int, str] = {}
        self._free: list[int] = []
        self._next_slot = 0
        # bumped on every mutation that changed any plane bit; the plane
        # uses it to invalidate cached device copies
        self.version = 0
        # capacity generation: bumped whenever the host planes must be
        # re-pushed wholesale (growth, compaction, row refresh, restore)
        self.capacity_generation = 0
        # the delta queue: (plane_id, row, word) cells + floor words the
        # next device sync patches in ONE apply_subscription_deltas
        # dispatch — O(cells), never O(population)
        self.dirty_cells: set[tuple[int, int, int]] = set()
        self.dirty_floor_words: set[int] = set()
        self._alloc_planes()
        # engine-registry version the symbol rows were resolved against
        self._rows_version: int | None = None

    # -- plane storage -------------------------------------------------------

    def _alloc_planes(self) -> None:
        u32 = self.capacity // 32
        # one trailing always-zero row: the "no such symbol" bucket a
        # match can gather when a fired symbol no longer resolves to an
        # engine row (delisted between dispatch and finalize) — explicit
        # subscribers get nothing, wildcards still match via any_masks
        self.sym_plane = np.zeros((self.symbol_capacity + 1, u32), np.uint32)
        self.strat_plane = np.zeros((len(STRATEGY_ORDER), u32), np.uint32)
        self.regime_plane = np.zeros((REGIME_ROWS, u32), np.uint32)
        self.any_masks = np.zeros((3, u32), np.uint32)
        self.floors = np.full(self.capacity, np.inf, np.float32)

    def _clear_dirty(self) -> None:
        self.dirty_cells.clear()
        self.dirty_floor_words.clear()

    @property
    def words(self) -> int:
        return self.capacity // 32

    def __len__(self) -> int:
        return len(self._records)

    def __contains__(self, user_id: str) -> bool:
        return user_id in self._records

    def get(self, user_id: str) -> Subscription | None:
        rec = self._records.get(user_id)
        return rec.sub if rec is not None else None

    def slot_of(self, user_id: str) -> int | None:
        rec = self._records.get(user_id)
        return rec.slot if rec is not None else None

    def user_of(self, slot: int) -> str | None:
        return self._slot_user.get(int(slot))

    def users_of_slots(self, slots: Iterable[int]) -> list[str]:
        return [
            u for u in (self._slot_user.get(int(s)) for s in slots)
            if u is not None
        ]

    # -- churn ---------------------------------------------------------------

    def _claim_slot(self) -> int:
        if self._free:
            return self._free.pop()
        if self._next_slot >= self.capacity:
            self._grow()
        slot = self._next_slot
        self._next_slot += 1
        return slot

    def _grow(self) -> None:
        """Double the slot capacity. Slots never move on growth and words
        are append-only in the packed layout, so growth PADS each plane
        with zero words on the right — bit-identical to a from-scratch
        replay (pinned by tests) without materializing a single record.
        Counted by the plane as a FULL device recompile (and the match
        kernel's one legitimate retrace)."""
        self.capacity *= 2
        u32 = self.capacity // 32

        def _wide(plane: np.ndarray) -> np.ndarray:
            out = np.zeros((plane.shape[0], u32), np.uint32)
            out[:, : plane.shape[1]] = plane
            return out

        self.sym_plane = _wide(self.sym_plane)
        self.strat_plane = _wide(self.strat_plane)
        self.regime_plane = _wide(self.regime_plane)
        self.any_masks = _wide(self.any_masks)
        floors = np.full(self.capacity, np.inf, np.float32)
        floors[: self.floors.shape[0]] = self.floors
        self.floors = floors
        self.capacity_generation += 1
        self._clear_dirty()  # full resync supersedes the delta queue

    def _set_bits(self, rec: _SlotRecord, on: bool) -> None:
        sub, slot = rec.sub, rec.slot
        w, bit = slot >> 5, np.uint32(1 << (slot & 31))
        planes_bits: list[tuple[int, np.ndarray, int]] = []
        if sub.symbols is None:
            planes_bits.append((P_ANY, self.any_masks, ANY_SYM))
        else:
            for row in rec.rows:
                planes_bits.append((P_SYM, self.sym_plane, row))
        if sub.strategies is None:
            planes_bits.append((P_ANY, self.any_masks, ANY_STRAT))
        else:
            for name in sub.strategies:
                planes_bits.append(
                    (P_STRAT, self.strat_plane, _STRAT_IDX[name])
                )
        if sub.regimes is None:
            planes_bits.append((P_ANY, self.any_masks, ANY_REGIME))
        else:
            for code in sub.regimes:
                planes_bits.append((P_REGIME, self.regime_plane, int(code)))
        if on:
            for _, plane, r in planes_bits:
                plane[r, w] |= bit
            self.floors[slot] = np.float32(sub.min_strength)
        else:
            inv = np.uint32(~bit)
            for _, plane, r in planes_bits:
                plane[r, w] &= inv
            self.floors[slot] = np.inf
        cells = self.dirty_cells
        for pid, _, r in planes_bits:
            cells.add((pid, r, w))
        self.dirty_floor_words.add(w)
        self.version += 1

    def _resolve_rows(
        self, symbols: frozenset[str] | None, row_of: Callable[[str], int | None]
    ) -> list[int]:
        if symbols is None:
            return []
        rows = (row_of(s) for s in symbols)
        return sorted(
            r for r in rows if r is not None and 0 <= r < self.symbol_capacity
        )

    def add(
        self,
        sub: Subscription,
        row_of: Callable[[str], int | None] | None = None,
    ) -> int:
        """Insert (or replace — churn ``update`` is remove+add on the SAME
        slot) one subscription; returns the user's slot. ``row_of``
        resolves symbol names to engine rows (None = unresolved yet; the
        plane re-resolves on its registry-version check)."""
        sub = Subscription(
            user_id=sub.user_id,
            symbols=_norm_symbols(sub.symbols),
            strategies=sub.strategies,
            regimes=sub.regimes,
            min_strength=sub.min_strength,
        )
        existing = self._records.get(sub.user_id)
        if existing is not None:
            self._set_bits(existing, on=False)
            slot = existing.slot
        else:
            slot = self._claim_slot()
        rec = _SlotRecord(sub=sub, slot=slot)
        if row_of is not None:
            rec.rows = self._resolve_rows(sub.symbols, row_of)
        self._records[sub.user_id] = rec
        if sub.symbols is not None:
            self._explicit.add(sub.user_id)
        else:
            self._explicit.discard(sub.user_id)
        self._slot_user[slot] = sub.user_id
        self._set_bits(rec, on=True)
        return slot

    def update(
        self,
        sub: Subscription,
        row_of: Callable[[str], int | None] | None = None,
    ) -> int:
        """Alias of :meth:`add` for churn-intent readability (slot kept)."""
        return self.add(sub, row_of=row_of)

    def remove(self, user_id: str) -> int | None:
        rec = self._records.pop(user_id, None)
        if rec is None:
            return None
        self._explicit.discard(user_id)
        self._set_bits(rec, on=False)
        del self._slot_user[rec.slot]
        self._free.append(rec.slot)
        return rec.slot

    def fragmentation(self) -> float:
        """Tombstone fraction of the claimed slot range — what the
        plane's compaction threshold compares against."""
        return len(self._free) / self._next_slot if self._next_slot else 0.0

    def compact(self) -> dict[str, tuple[int, int]]:
        """Fold tombstones back into dense planes: re-pack every live
        record into the lowest slots (stable old-slot order), shrink
        capacity back toward the initial allocation when occupancy
        allows, and rebuild the planes. Returns ``{user_id: (old_slot,
        new_slot)}`` for every user whose slot moved.

        A deliberate heavyweight pass (fragmentation-triggered, never
        steady-state churn): a lazily-restored population materializes
        here, and the plane counts the follow-up device sync as FULL.
        """
        recs = sorted(self._records.values(), key=lambda r: r.slot)
        n = len(recs)
        cap = self.capacity
        # keep >= 50% headroom above the live population so the compact
        # → grow → compact flap can't happen at a stable size
        while cap // 2 >= self._initial_capacity and 2 * n <= cap // 2:
            cap //= 2
        self.capacity = cap
        moved: dict[str, tuple[int, int]] = {}
        self._alloc_planes()
        self._slot_user.clear()
        self._free = []
        for new_slot, rec in enumerate(recs):
            if rec.slot != new_slot:
                moved[rec.sub.user_id] = (rec.slot, new_slot)
                rec.slot = new_slot
            self._slot_user[new_slot] = rec.sub.user_id
            self._set_bits(rec, on=True)
        self._next_slot = n
        self.capacity_generation += 1
        self._clear_dirty()  # the full resync supersedes the delta queue
        self.version += 1
        return moved

    def bulk_load(
        self,
        subs: Iterable[Subscription],
        row_of: Callable[[str], int | None] | None = None,
    ) -> int:
        """Vectorized initial load (the 1M-subscription path): one grouped
        ``np.bitwise_or.at`` pass per plane instead of per-user bit flips.
        Produces planes IDENTICAL to sequential :meth:`add` calls (pinned
        by tests). Returns the number of users loaded."""
        subs = list(subs)
        # validate BEFORE any mutation: a duplicate found mid-loop would
        # otherwise leave earlier records registered without plane bits
        # (a silent device-vs-oracle divergence no later sync repairs)
        seen: set[str] = set()
        for raw in subs:
            if raw.user_id in self._records or raw.user_id in seen:
                raise ValueError(
                    f"bulk_load of existing user {raw.user_id!r}; use "
                    "update() for churn"
                )
            seen.add(raw.user_id)
        need = self._next_slot + len(subs) - len(self._free)
        while need > self.capacity:
            self._grow()
        sym_i: list[int] = []
        sym_w: list[int] = []
        sym_b: list[int] = []
        strat_i: list[int] = []
        strat_w: list[int] = []
        strat_b: list[int] = []
        reg_i: list[int] = []
        reg_w: list[int] = []
        reg_b: list[int] = []
        any_i: list[int] = []
        any_w: list[int] = []
        any_b: list[int] = []
        slots = np.empty(len(subs), np.int64)
        floors = np.empty(len(subs), np.float32)
        for k, raw in enumerate(subs):
            sub = Subscription(
                user_id=raw.user_id,
                symbols=_norm_symbols(raw.symbols),
                strategies=raw.strategies,
                regimes=raw.regimes,
                min_strength=raw.min_strength,
            )
            slot = self._claim_slot()
            rec = _SlotRecord(sub=sub, slot=slot)
            if row_of is not None:
                rec.rows = self._resolve_rows(sub.symbols, row_of)
            self._records[sub.user_id] = rec
            if sub.symbols is not None:
                self._explicit.add(sub.user_id)
            self._slot_user[slot] = sub.user_id
            slots[k] = slot
            floors[k] = sub.min_strength
            w, b = slot >> 5, slot & 31
            if sub.symbols is None:
                any_i.append(ANY_SYM); any_w.append(w); any_b.append(b)
            else:
                for row in rec.rows:
                    sym_i.append(row); sym_w.append(w); sym_b.append(b)
            if sub.strategies is None:
                any_i.append(ANY_STRAT); any_w.append(w); any_b.append(b)
            else:
                for name in sub.strategies:
                    strat_i.append(_STRAT_IDX[name])
                    strat_w.append(w); strat_b.append(b)
            if sub.regimes is None:
                any_i.append(ANY_REGIME); any_w.append(w); any_b.append(b)
            else:
                for code in sub.regimes:
                    reg_i.append(int(code)); reg_w.append(w); reg_b.append(b)
        one = np.uint32(1)
        groups = (
            (P_SYM, self.sym_plane, sym_i, sym_w, sym_b),
            (P_STRAT, self.strat_plane, strat_i, strat_w, strat_b),
            (P_REGIME, self.regime_plane, reg_i, reg_w, reg_b),
            (P_ANY, self.any_masks, any_i, any_w, any_b),
        )
        for _, plane, ii, ww, bb in groups:
            if ii:
                np.bitwise_or.at(
                    plane,
                    (np.asarray(ii, np.int64), np.asarray(ww, np.int64)),
                    one << np.asarray(bb, np.uint32),
                )
        self.floors[slots] = floors
        if len(subs) * 4 >= self.capacity:
            # a load touching a large fraction of the plane resyncs
            # faster as one full push than as O(load) word scatters
            self.capacity_generation += 1
            self._clear_dirty()
        else:
            cells = self.dirty_cells
            for pid, _, ii, ww, _b in groups:
                cells.update((pid, i, w) for i, w in zip(ii, ww))
            self.dirty_floor_words.update(
                int(w) for w in np.unique(slots >> 5)
            )
        self.version += 1
        return len(subs)

    # -- symbol-row refresh --------------------------------------------------

    def refresh_rows(
        self, row_of: Callable[[str], int | None], registry_version: int
    ) -> bool:
        """Re-resolve every explicit symbol subscription against the
        engine registry when its ``version`` moved (listing churn re-homes
        rows). Rebuilds ``sym_plane`` from scratch — symbol churn is rare
        and row reuse makes per-row patching unsound (a freed row's old
        bits must vanish). Returns True when anything was rebuilt."""
        if self._rows_version == registry_version:
            return False
        self._rows_version = registry_version
        if not self._explicit:
            # wildcard-only population: sym_plane is all zero and stays
            # so — recording the version is enough; forcing a full device
            # re-push here would re-upload megabytes of unchanged planes
            # on every engine listing-churn version bump
            return False
        self.sym_plane.fill(0)
        # only EXPLICIT symbol subscriptions re-resolve (the _explicit
        # index keeps listing churn O(explicit subs), not O(population));
        # bits land in one grouped scatter instead of per-record writes
        rr: list[int] = []
        ww: list[int] = []
        bb: list[int] = []
        for uid in self._explicit:
            rec = self._records[uid]
            rec.rows = self._resolve_rows(rec.sub.symbols, row_of)
            if rec.rows:
                w, b = rec.slot >> 5, rec.slot & 31
                rr.extend(rec.rows)
                ww.extend([w] * len(rec.rows))
                bb.extend([b] * len(rec.rows))
        if rr:
            np.bitwise_or.at(
                self.sym_plane,
                (np.asarray(rr, np.int64), np.asarray(ww, np.int64)),
                np.uint32(1) << np.asarray(bb, np.uint32),
            )
        # every word column of sym_plane may have changed: force a full
        # device resync rather than enumerating all cells as dirty
        self.capacity_generation += 1
        self._clear_dirty()
        self.version += 1
        return True

    # -- snapshot-warm boot (ISSUE 20) ---------------------------------------

    def export_columns(self) -> dict[str, np.ndarray]:
        """Slot-ordered columnar image of the subscription index — what
        the snapshot sidecar archives next to the raw planes. Lazy
        (never-touched) restored records export straight from their
        columns; criteria lists are sorted, so the archive bytes are
        deterministic for a given population."""
        rows = sorted(self._records.export_rows(), key=lambda t: t[1])
        uids: list[str] = []
        slots: list[int] = []
        sym_counts: list[int] = []
        sym_names: list[str] = []
        strat_counts: list[int] = []
        strat_names: list[str] = []
        reg_counts: list[int] = []
        reg_flat: list[int] = []
        row_counts: list[int] = []
        rows_flat: list[int] = []
        for uid, slot, syms, strats, regs, rrows in rows:
            if "\n" in uid:
                # the archive joins ids on newline; a newline-bearing uid
                # would silently split on restore — refuse loudly instead
                raise ValueError(
                    f"user id {uid!r} contains a newline; not archivable"
                )
            uids.append(uid)
            slots.append(slot)
            if syms is None:
                sym_counts.append(-1)
            else:
                sym_counts.append(len(syms))
                sym_names.extend(syms)
            if strats is None:
                strat_counts.append(-1)
            else:
                strat_counts.append(len(strats))
                strat_names.extend(strats)
            if regs is None:
                reg_counts.append(-1)
            else:
                reg_counts.append(len(regs))
                reg_flat.extend(int(r) for r in regs)
            row_counts.append(len(rrows))
            rows_flat.extend(int(r) for r in rrows)

        def _blob(parts: list[str]) -> np.ndarray:
            if not parts:
                return np.zeros(0, np.uint8)
            return np.frombuffer(
                "\n".join(parts).encode("utf-8"), np.uint8
            ).copy()

        return {
            "uid_blob": _blob(uids),
            "slots": np.asarray(slots, np.int64),
            "sym_counts": np.asarray(sym_counts, np.int32),
            "sym_blob": _blob(sym_names),
            "strat_counts": np.asarray(strat_counts, np.int32),
            "strat_blob": _blob(strat_names),
            "reg_counts": np.asarray(reg_counts, np.int32),
            "reg_flat": np.asarray(reg_flat, np.int16),
            "row_counts": np.asarray(row_counts, np.int32),
            "rows_flat": np.asarray(rows_flat, np.int32),
            "free_slots": np.asarray(sorted(self._free), np.int32),
        }

    def restore_columns(
        self,
        planes: dict[str, np.ndarray],
        columns: dict[str, np.ndarray],
        capacity: int,
        next_slot: int,
        rows_version: int | None,
    ) -> int:
        """Adopt a snapshot archive wholesale: plane arrays become the
        host truth, the columnar subscription index attaches as a LAZY
        record base (per-user materialization on first touch), and the
        device copy is invalidated for one full push. ``rows_version``
        is the engine-registry version the archived rows are valid for
        (None = unknown/mismatched → the next sync's ``refresh_rows``
        rebuilds sym_plane the slow, safe way). Returns the restored
        user count."""
        capacity = int(capacity)
        assert capacity % 32 == 0 and capacity >= 32, capacity
        self.capacity = capacity
        self.sym_plane = np.ascontiguousarray(planes["sym_plane"], np.uint32)
        self.strat_plane = np.ascontiguousarray(
            planes["strat_plane"], np.uint32
        )
        self.regime_plane = np.ascontiguousarray(
            planes["regime_plane"], np.uint32
        )
        self.any_masks = np.ascontiguousarray(planes["any_masks"], np.uint32)
        self.floors = np.ascontiguousarray(planes["floors"], np.float32)
        base = _ColumnarBase(columns, self.floors)
        self._records = _RecordMap()
        self._records.attach_base(base)
        self._slot_user = dict(zip(base.slots.tolist(), base.uids))
        counts = base.sym_counts.tolist()
        self._explicit = {
            u for u, c in zip(base.uids, counts) if c >= 0
        }
        self._free = [int(s) for s in columns["free_slots"]]
        self._next_slot = int(next_slot)
        self.version += 1
        self.capacity_generation += 1  # device must take one full push
        self._clear_dirty()
        self._rows_version = rows_version
        return len(base.uids)

    # -- oracle --------------------------------------------------------------

    def match_oracle(
        self,
        entries: list[tuple[str, str, float]],
        regime: int | None,
        unresolved: frozenset[str] = frozenset(),
    ) -> list[set[str]]:
        """Per-entry recipient user-id sets for ``(strategy, symbol,
        score)`` fired entries — the pure-Python reference the device
        kernel's packed output must equal exactly. ``unresolved`` names
        fired symbols with NO current engine row (delisted between
        dispatch and finalize): the kernel gathers the empty no-row
        bucket for those, so explicit-symbol subscribers do not match —
        only wildcards do — and the oracle must agree."""
        out: list[set[str]] = []
        for strategy, symbol, score in entries:
            sym = symbol.strip().upper()
            out.append(
                {
                    rec.sub.user_id
                    for rec in self._records.values()
                    if rec.sub.matches(strategy, sym, score, regime)
                    and not (
                        rec.sub.symbols is not None and sym in unresolved
                    )
                }
            )
        return out

    def snapshot(self) -> dict:
        """Attribute-read stats for /healthz and the flight recorder."""
        return {
            "users": len(self._records),
            "capacity": self.capacity,
            "words": self.words,
            "version": self.version,
            "dirty_cells": len(self.dirty_cells),
            "dirty_floor_words": len(self.dirty_floor_words),
            "free_slots": len(self._free),
            "lazy_records": self._records.lazy_count,
        }
