"""Production-breadth validation (VERDICT r4 item 5).

The checked-in market fixture is 100 symbols; the production claim is
thousands. This suite generates a seeded 1024-symbol session on the fly
(``io/market_sim.py`` — stylized-facts generator, nothing checked in),
replays it through the PRODUCTION engine with the PRODUCTION context
gates (``ContextConfig()``: >=40 fresh / >=70% coverage — the reference's
``live_market_context_accumulator.py:13-14``), and asserts the behaviors
crafted unit vectors cannot exercise at scale:

* the coverage gate opens (signals only exist if >=40 fresh & >=70%
  coverage held on fired ticks);
* every rolling-percentile threshold stays selective at breadth (the
  pathology class of ABP's 92nd-percentile trigger,
  ``/root/reference/strategies/activity_burst_pump.py:134-139``:
  fire-always / fire-never);
* per-tick signal counts stay in the same band the 100-symbol fixture
  established (scaled by universe size).
"""

from __future__ import annotations

from collections import Counter

import pytest

pytestmark = pytest.mark.slow

S = 1024
WINDOW = 200
T0 = 1_753_000_200


@pytest.fixture(scope="module")
def breadth_run(tmp_path_factory):
    from binquant_tpu.io.market_sim import MarketSimConfig, write_market_file
    from binquant_tpu.io.replay import run_replay
    from binquant_tpu.regime.context import ContextConfig

    path = tmp_path_factory.mktemp("breadth") / "market_1024.jsonl.gz"
    write_market_file(path, MarketSimConfig(n_symbols=S, seed=20250731), t0=T0)

    collect: list = []
    stats = run_replay(
        path,
        capacity=S,
        window=WINDOW,
        collect=collect,
        context_config=ContextConfig(),  # production gates: 40 / 0.70
    )
    return stats, collect


def test_context_gate_opens_at_production_breadth(breadth_run):
    """With the production 40-fresh/70%-coverage gate, a full-breadth
    session must produce a valid context and therefore signals — if the
    gate never opened, every context-gated strategy would stay silent."""
    stats, collect = breadth_run
    counts = Counter(s[1] for s in collect)
    assert stats["ticks"] >= 100
    # PriceTracker requires a VALID context (RANGE regime + stable
    # breadth): any PT signal proves the coverage gate opened at scale
    assert counts["coinrule_price_tracker"] >= 1, counts


def test_percentile_thresholds_stay_selective_at_breadth(breadth_run):
    """Rolling-quantile triggers (ABP's 92nd percentile, LSP's 80th) must
    neither degenerate to fire-always nor collapse to fire-never when the
    cross-section is 10x wider."""
    stats, collect = breadth_run
    counts = Counter(s[1] for s in collect)
    opportunities = stats["ticks"] * S
    assert counts["activity_burst_pump"] >= 1, counts
    assert counts["mean_reversion_fade"] >= 1, counts
    for strategy, n in counts.items():
        rate = n / opportunities
        assert rate < 0.02, f"{strategy} fires {rate:.2%} of symbol-ticks"


def test_per_tick_signal_counts_in_band(breadth_run):
    """Per-tick fired counts at 1024 symbols: calm-market ticks stay
    proportionate to the 100-symbol fixture's behavior, while the cascade
    tick legitimately fires market-wide (MRF's prey: the seeded session's
    bottom tick fires ~900 of 1024 rows) and MUST take the wire-overflow
    fallback — compaction sizing exercised at production breadth, not
    just in the crafted burst drill."""
    stats, collect = breadth_run
    per_tick = Counter(t for t, *_ in collect)
    events_open_ms = (T0 + 27 * 3600) * 1000
    calm_max = max(
        (n for t, n in per_tick.items() if t < events_open_ms), default=0
    )
    assert calm_max <= S // 4, calm_max
    # the market-wide cascade exceeds WIRE_MAX_FIRED -> overflow fallback
    # ran, and its signals still arrived (they are in `collect`)
    assert stats["overflow_ticks"] >= 1
    assert max(per_tick.values()) > S // 2
    # signals concentrate in the eventful window (hour >= 27), as on the
    # 100-symbol fixture
    eventful = sum(1 for t, *_ in collect if t >= events_open_ms)
    assert eventful / len(collect) >= 0.5
