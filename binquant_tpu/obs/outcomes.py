"""Signal-outcome observatory: device-side forward-return attribution.

Four observability layers can say *when* and *how healthy* a signal was
emitted (metrics, traces, numeric health, latency); this one says whether
it was any *good*. Every emitted signal registers here (strategy, symbol
row, the evaluated 5m bar as the entry anchor, trace_id/tick_seq as the
join key back to the ``signal`` event) and matures at fixed horizons —
bars of the 5m series (:data:`DEFAULT_HORIZONS`) — via ONE jit'd batched
gather over the open rows against the live ring per maturation tick: no
per-signal Python loops, no extra history copies on the host.

The gather is **timestamp-bounded**, not recency-bounded: a (slot,
horizon) pair reads exactly the ring bars with ``entry_ts < t <=
entry_ts + horizon*300`` plus the entry bar itself, so WHEN maturation
runs is irrelevant to WHAT it computes — the serial drive maturing
per-tick and the scanned/backtest drives maturing through a
post-chunk ring (their finalize loop runs after the chunk commits, so
the ring already holds newer bars) produce the identical matured set.
The one retention requirement: the ring must still HOLD the pair's
window when maturation reaches it — ``W >= 3 * chunk_ticks +
max(horizons)`` 5m bars (three 5m bars land per 15m tick). A clipped
window is detected via the ring's oldest retained bar and the outcome
is marked ``truncated`` (excluded from metrics, counted) instead of
silently computing on partial history.

Outcome sign convention (direction-relative return space, so LONG and
SHORT share one scale):

* ``fwd_ret``  — signed forward return at the horizon close
  (``direction * (fwd_close / entry_close - 1)``); a hit is
  ``fwd_ret > 0``.
* ``mae`` — max adverse excursion, always ``<= 0``: the worst
  signed-return drawdown within the horizon (LONG: the lowest low;
  SHORT: the highest high).
* ``mfe`` — max favorable excursion, always ``>= 0``: the best
  signed-return run-up within the horizon.

The open registry is bounded (``cap`` slots; registering past it evicts
the OLDEST open signal and counts ``bqt_signal_outcome_evictions_total``)
and survives checkpoint save/restore through the engine's host-carries
JSON (:meth:`OutcomeTracker.snapshot_open` / :meth:`restore_open`) — a
restart mid-horizon matures the same ``signal_outcome`` set as an
uninterrupted run (tests/test_outcomes.py pins this).

Knob: ``BQT_OUTCOMES`` — default ON in production, pinned 0 in the
tier-1 conftest and in bench throughput arms (the BQT_TRACE_SAMPLE lane
split). ``BQT_OUTCOME_HORIZONS`` / ``BQT_OUTCOME_CAP`` size the bed.
"""

from __future__ import annotations

from collections import deque
from typing import Any

import numpy as np

from binquant_tpu.obs.events import get_event_log
from binquant_tpu.obs.instruments import (
    OUTCOME_EVICTIONS,
    OUTCOME_MATURED,
    OUTCOME_OPEN,
    OUTCOME_TRUNCATED,
    SIGNAL_FWD_RETURN,
    SIGNAL_HIT_RATE,
    SIGNAL_MAE,
    SIGNAL_MFE,
)

#: Maturation horizons in 5m bars: next bar, ~20 min, ~80 min, ~8 h.
DEFAULT_HORIZONS: tuple[int, ...] = (1, 4, 16, 96)

FIVE_MIN_S = 300


def _outcome_gather_impl(times, values, rows, entry_ts, horizon_ts):
    """The one device pass per maturation tick.

    ``times`` (S, W) / ``values`` (S, W, F) are the LIVE 5m ring arrays —
    raw ring order, any cursor phase: every reduction below is a
    timestamp-masked scan, so bar order in memory is irrelevant (the same
    property the circular-cursor rings rely on). ``rows`` (K,) selects
    the open slots' symbol rows (padding slots are -1), ``entry_ts`` /
    ``horizon_ts`` (K,) bound each pair's window in bar-open seconds.

    Returns ``(f32 (4, K), i32 (2, K))``: entry close (the last bar at or
    before the entry anchor), horizon close (the last bar inside the
    window), window min-low and max-high; then bars-found and the row's
    oldest retained bar ts (the host's truncation judge — returned as
    exact int32, f32 would quantize ~1.7e9-second stamps to ±128 s).
    """
    import jax.numpy as jnp

    from binquant_tpu.engine.buffer import Field

    S = times.shape[0]
    safe = jnp.clip(rows, 0, S - 1)
    t = times[safe]  # (K, W)
    v = values[safe]  # (K, W, F)
    live = (t >= 0) & (rows[:, None] >= 0)
    close = v[:, :, Field.CLOSE]
    high = v[:, :, Field.HIGH]
    low = v[:, :, Field.LOW]
    in_win = live & (t > entry_ts[:, None]) & (t <= horizon_ts[:, None])
    at_entry = live & (t <= entry_ts[:, None])

    def last_close(sel):
        has = jnp.any(sel, axis=1)
        idx = jnp.argmax(jnp.where(sel, t, jnp.int32(-(2**31))), axis=1)
        c = jnp.take_along_axis(close, idx[:, None], axis=1)[:, 0]
        return jnp.where(has, c, jnp.nan)

    any_win = jnp.any(in_win, axis=1)
    min_low = jnp.min(jnp.where(in_win, low, jnp.inf), axis=1)
    max_high = jnp.max(jnp.where(in_win, high, -jnp.inf), axis=1)
    floats = jnp.stack(
        [
            last_close(at_entry),
            last_close(in_win),
            jnp.where(any_win, min_low, jnp.nan).astype(jnp.float32),
            jnp.where(any_win, max_high, jnp.nan).astype(jnp.float32),
        ]
    )
    oldest = jnp.min(
        jnp.where(live, t, jnp.int32(2**31 - 1)), axis=1
    )
    ints = jnp.stack(
        [jnp.sum(in_win, axis=1).astype(jnp.int32), oldest]
    )
    return floats, ints


# jit'd lazily so importing this module never drags jax in (the obs
# package idiom — instruments/events stay importable in jax-free tools)
_outcome_gather_jit = None


def outcome_gather(times, values, rows, entry_ts, horizon_ts):
    """Host entry for the maturation kernel: pad-free numpy in, numpy out
    (callers pad ``rows`` to a power-of-two bucket themselves — the pad
    policy bounds the executable count and lives with the caller)."""
    global _outcome_gather_jit
    import jax
    import jax.numpy as jnp

    if _outcome_gather_jit is None:
        _outcome_gather_jit = jax.jit(_outcome_gather_impl)
    floats, ints = _outcome_gather_jit(
        times,
        values,
        jnp.asarray(np.asarray(rows, np.int32)),
        jnp.asarray(np.asarray(entry_ts, np.int32)),
        jnp.asarray(np.asarray(horizon_ts, np.int32)),
    )
    return np.asarray(floats), np.asarray(ints)


def signed_outcome(
    direction: int,
    entry_close: float,
    fwd_close: float,
    min_low: float,
    max_high: float,
) -> tuple[float, float, float] | None:
    """(fwd_ret, mae, mfe) in direction-relative return space, or None
    when the raw gather was unusable (no entry bar / empty window /
    non-positive entry). One copy of the sign convention — the live
    tracker and the sweep scorer both fold raw gathers through here."""
    if not (
        entry_close == entry_close
        and fwd_close == fwd_close
        and min_low == min_low
        and max_high == max_high
        and entry_close > 0
    ):
        return None
    fwd_raw = fwd_close / entry_close - 1.0
    lo = min_low / entry_close - 1.0
    hi = max_high / entry_close - 1.0
    if direction >= 0:
        return fwd_raw, min(0.0, lo), max(0.0, hi)
    return -fwd_raw, min(0.0, -hi), max(0.0, -lo)


def direction_sign(direction: Any) -> int:
    """'SHORT'/Direction.SHORT/1 → -1; everything else (LONG, grid) +1."""
    s = str(direction)
    if s in ("SHORT", "1", "Direction.SHORT"):
        return -1
    return 1


def _pow2(n: int, floor: int = 8) -> int:
    """The ONE pad-bucket policy for the maturation gather's pair axis
    (the live tracker and the sweep scorer both pad through here — the
    bucket policy directly controls the gather's executable count; the
    scan lanes' io.pipeline._pow2_bucket is a separate policy for a
    separate executable family)."""
    size = floor
    while size < n:
        size *= 2
    return size


class _Agg:
    """Per-(strategy, horizon) scoreboard cell."""

    __slots__ = ("n", "hits", "sum_fwd", "sum_mae", "sum_mfe", "worst_mae")

    def __init__(self) -> None:
        self.n = 0
        self.hits = 0
        self.sum_fwd = 0.0
        self.sum_mae = 0.0
        self.sum_mfe = 0.0
        self.worst_mae = 0.0

    def add(self, fwd: float, mae: float, mfe: float) -> None:
        self.n += 1
        self.hits += 1 if fwd > 0 else 0
        self.sum_fwd += fwd
        self.sum_mae += mae
        self.sum_mfe += mfe
        self.worst_mae = min(self.worst_mae, mae)

    def as_dict(self) -> dict:
        n = self.n
        return {
            "n": n,
            "hits": self.hits,
            "hit_rate": round(self.hits / n, 4) if n else None,
            "avg_fwd": round(self.sum_fwd / n, 6) if n else None,
            "avg_mae": round(self.sum_mae / n, 6) if n else None,
            "avg_mfe": round(self.sum_mfe / n, 6) if n else None,
            "worst_mae": round(self.worst_mae, 6) if n else None,
        }


class OutcomeTracker:
    """Open-signal registry + maturation driver for one engine."""

    def __init__(
        self,
        enabled: bool = True,
        horizons: tuple[int, ...] = DEFAULT_HORIZONS,
        cap: int = 1024,
    ) -> None:
        self.horizons = tuple(
            sorted({int(h) for h in (horizons or ()) if int(h) > 0})
        )
        # no positive horizons = the observatory is off (an operator's
        # BQT_OUTCOME_HORIZONS=0 is a disable, not a boot crash)
        self.enabled = bool(enabled) and bool(self.horizons)
        self.cap = max(int(cap), 1)
        # open slots in registration order (eviction pops the head); each
        # slot is one emitted signal with its not-yet-matured horizons
        self._open: deque[dict] = deque()
        self.registered = 0
        self.evictions = 0
        self.matured = 0  # (signal, horizon) pairs matured
        self.truncated = 0  # matured pairs whose ring window was clipped
        self._agg: dict[tuple[str, int], _Agg] = {}
        # matured comparison tuples (strategy, symbol, entry_ts, horizon,
        # fwd, mae, mfe, bars) — the parity/test surface, ring-bounded so
        # a long-lived live engine cannot grow it without bound
        self.recent: deque[tuple] = deque(maxlen=8192)

    # -- registration --------------------------------------------------------

    def register(
        self,
        strategy: str,
        symbol: str,
        row: int,
        entry_ts5: int,
        direction: Any,
        trace_id: str | None = None,
        tick_seq: int | None = None,
        tick_ms: int | None = None,
    ) -> None:
        """One emitted signal enters the open registry. ``entry_ts5`` is
        the evaluated 5m bar's OPEN time (seconds) — its close is the
        entry anchor, gathered from the ring at maturation so every drive
        anchors on the identical bar, not on a per-strategy payload
        field."""
        if not self.enabled:
            return
        if len(self._open) >= self.cap:
            self._open.popleft()
            self.evictions += 1
            OUTCOME_EVICTIONS.inc()
        self._open.append(
            {
                "strategy": strategy,
                "symbol": symbol,
                "row": int(row),
                "entry_ts": int(entry_ts5),
                "dir": direction_sign(direction),
                "trace_id": trace_id,
                "tick_seq": tick_seq,
                "tick_ms": tick_ms,
                "pending": list(self.horizons),
            }
        )
        self.registered += 1
        OUTCOME_OPEN.set(len(self._open))

    # -- maturation ----------------------------------------------------------

    def due_pairs(self, now_ts5: int) -> list[tuple[dict, int]]:
        """(slot, horizon) pairs whose horizon bar has closed by the tick
        evaluating the 5m bar that opens at ``now_ts5``."""
        out: list[tuple[dict, int]] = []
        for slot in self._open:
            for h in slot["pending"]:
                if slot["entry_ts"] + h * FIVE_MIN_S <= now_ts5:
                    out.append((slot, h))
        return out

    def on_tick(self, now_ts5: int, buf5) -> list[tuple]:
        """Mature everything due at this tick against the live 5m ring.
        Returns the newly matured comparison tuples (also appended to
        ``self.recent`` and emitted as ``signal_outcome`` events)."""
        if not self.enabled or not self._open:
            return []
        pairs = self.due_pairs(int(now_ts5))
        if not pairs:
            return []
        K = _pow2(len(pairs))
        rows = np.full(K, -1, np.int32)
        entry = np.zeros(K, np.int32)
        horizon = np.zeros(K, np.int32)
        for i, (slot, h) in enumerate(pairs):
            rows[i] = slot["row"]
            entry[i] = slot["entry_ts"]
            horizon[i] = slot["entry_ts"] + h * FIVE_MIN_S
        floats, ints = outcome_gather(
            buf5.times, buf5.values, rows, entry, horizon
        )
        matured: list[tuple] = []
        touched: set[tuple[str, int]] = set()
        for i, (slot, h) in enumerate(pairs):
            slot["pending"].remove(h)
            # plain Python floats: the values land in JSON events and the
            # checkpoint blob — numpy scalars would serialize per-platform
            outcome = signed_outcome(
                slot["dir"], float(floats[0, i]), float(floats[1, i]),
                float(floats[2, i]), float(floats[3, i]),
            )
            # the ring must still hold the pair's whole window: its oldest
            # retained bar at or before the entry anchor (the entry bar
            # itself doubles as the boundary witness)
            clipped = int(ints[1, i]) > slot["entry_ts"]
            self.matured += 1
            event: dict[str, Any] = {
                "strategy": slot["strategy"],
                "symbol": slot["symbol"],
                "horizon": h,
                "entry_ts": slot["entry_ts"],
                "bars": int(ints[0, i]),
                "tick_ms": slot["tick_ms"],
                "trace_id": slot["trace_id"],
                "tick_seq": slot["tick_seq"],
                "direction": "SHORT" if slot["dir"] < 0 else "LONG",
            }
            if outcome is None or clipped:
                self.truncated += 1
                OUTCOME_TRUNCATED.inc()
                event["truncated"] = True
                get_event_log().emit("signal_outcome", **event)
                continue
            fwd, mae, mfe = outcome
            key = (slot["strategy"], h)
            self._agg.setdefault(key, _Agg()).add(fwd, mae, mfe)
            touched.add(key)
            hl = str(h)
            SIGNAL_FWD_RETURN.labels(
                strategy=slot["strategy"], horizon=hl
            ).observe(fwd)
            SIGNAL_MAE.labels(strategy=slot["strategy"], horizon=hl).observe(
                mae
            )
            SIGNAL_MFE.labels(strategy=slot["strategy"], horizon=hl).observe(
                mfe
            )
            OUTCOME_MATURED.labels(
                strategy=slot["strategy"], horizon=hl
            ).inc()
            event.update(
                fwd_ret=round(fwd, 6), mae=round(mae, 6), mfe=round(mfe, 6)
            )
            get_event_log().emit("signal_outcome", **event)
            tup = (
                slot["strategy"],
                slot["symbol"],
                slot["entry_ts"],
                h,
                round(fwd, 6),
                round(mae, 6),
                round(mfe, 6),
                int(ints[0, i]),
            )
            self.recent.append(tup)
            matured.append(tup)
        for strategy, h in touched:
            agg = self._agg[(strategy, h)]
            SIGNAL_HIT_RATE.labels(strategy=strategy, horizon=str(h)).set(
                agg.hits / agg.n
            )
        # drop fully-matured slots (registration order preserved)
        self._open = deque(s for s in self._open if s["pending"])
        OUTCOME_OPEN.set(len(self._open))
        return matured

    # -- introspection / persistence -----------------------------------------

    def scoreboard(self) -> dict:
        """/healthz ``outcomes`` section + report surface."""
        per_strategy: dict[str, dict[str, dict]] = {}
        for (strategy, h), agg in sorted(self._agg.items()):
            per_strategy.setdefault(strategy, {})[str(h)] = agg.as_dict()
        return {
            "enabled": self.enabled,
            "horizons": list(self.horizons),
            "cap": self.cap,
            "open": len(self._open),
            "registered": self.registered,
            "matured": self.matured,
            "truncated": self.truncated,
            "evictions": self.evictions,
            "per_strategy": per_strategy,
        }

    def snapshot_open(self) -> list[dict]:
        """JSON-safe open-registry snapshot for the checkpoint's
        host-carries blob (aggregates are observability state and restart
        fresh; the OPEN signals are correctness state — a restart
        mid-horizon must mature the same set an uninterrupted run would)."""
        return [dict(slot, pending=list(slot["pending"])) for slot in self._open]

    def restore_open(self, slots: list[dict] | None) -> None:
        # a disabled tracker must not adopt an outcomes-on checkpoint's
        # open registry: register/on_tick would never mature or clear the
        # slots, leaving phantom registry pressure in every snapshot
        if not slots or not self.enabled:
            return
        for slot in slots:
            self._open.append(
                {
                    "strategy": str(slot["strategy"]),
                    "symbol": str(slot["symbol"]),
                    "row": int(slot["row"]),
                    "entry_ts": int(slot["entry_ts"]),
                    "dir": int(slot.get("dir", 1)),
                    "trace_id": slot.get("trace_id"),
                    "tick_seq": slot.get("tick_seq"),
                    "tick_ms": slot.get("tick_ms"),
                    "pending": [int(h) for h in slot["pending"]],
                }
            )
        while len(self._open) > self.cap:
            self._open.popleft()
            self.evictions += 1
            OUTCOME_EVICTIONS.inc()
        OUTCOME_OPEN.set(len(self._open))

    def matured_set(self) -> set[tuple]:
        """The matured comparison tuples (parity harness surface)."""
        return set(self.recent)
