"""Extension-invariant precompute + batch decode drills (ISSUE 17).

The backtest chunk body's precompute historically vmapped every feature
kernel T times over gathered (T, S, W) window views. ``BQT_EXT_INVARIANT=1``
replaces that with ONE pass per kernel over the (S, W+T) extension
(``_precompute_ext``), governed by the gate-margin tolerance contract
(strategies/params.py ``declared_gate_margins``; README §Backtest):

* positional fields (bar values, times, filled, BTC positional gathers)
  must be BIT-identical between the two precompute paths;
* windowed cumsum/EWM fields are ulp/margin-governed — same NaN pattern,
  tight numeric tolerance, and fired-set flips only admissible inside the
  declared margin band (pinned here at the chunk-kernel level and by the
  end-to-end set-equality drill);
* the batch wire decode (``unpack_wire_block``) must return exactly the
  per-tick ``unpack_wire`` tuples, including the overflow flag and the
  digest/ingest side blocks.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from binquant_tpu.engine.buffer import Field, NUM_FIELDS


def _make_ext(S, W, N, seed=0, spacing=900, t0=1_780_272_000):
    """Synthetic (S, L) extension: right-aligned base with per-row history
    depth ``filled0`` in columns [0, W), one append per tick per row in
    columns [W, W+N). Bar times are tick-aligned across rows so freshness
    gates engage; values are a positive random walk."""
    rng = np.random.default_rng(seed)
    L = W + N
    ext_t = np.full((S, L), -1, np.int32)
    ext_v = np.full((S, L, NUM_FIELDS), np.nan, np.float32)
    # mixed history depth: warm rows (full window), partial rows, and one
    # nearly-empty row — the parity taxonomy's three regimes
    filled0 = np.full(S, W, np.int64)
    filled0[S // 2 :] = rng.integers(3, max(4, W // 2), size=S - S // 2)
    filled0[-1] = 1

    px = 20.0 + rng.random(S) * 60.0
    for j in range(L):
        # column j holds the bar for "global step" j - (W - 1): base bars
        # run back in time from column W-1, appends forward from column W
        ts = t0 + (j - (W - 1)) * spacing
        newpx = px * (1.0 + rng.normal(0.0, 0.004, S))
        row = np.empty((S, NUM_FIELDS), np.float32)
        row[:, Field.OPEN] = px
        row[:, Field.HIGH] = np.maximum(px, newpx) * 1.001
        row[:, Field.LOW] = np.minimum(px, newpx) * 0.999
        row[:, Field.CLOSE] = newpx
        row[:, Field.VOLUME] = 800.0 + 400.0 * rng.random(S)
        row[:, Field.QUOTE_VOLUME] = row[:, Field.VOLUME] * newpx
        row[:, Field.NUM_TRADES] = 300.0
        row[:, Field.TAKER_BUY_BASE] = row[:, Field.VOLUME] * 0.5
        row[:, Field.TAKER_BUY_QUOTE] = row[:, Field.QUOTE_VOLUME] * 0.5
        row[:, Field.DURATION_S] = float(spacing)
        px = newpx
        # per-row history depth: row r's base occupies its TRAILING
        # filled0[r] base columns
        keep = (j >= W - filled0) | (j >= W)
        ext_t[keep, j] = ts
        ext_v[keep, j] = row[keep]
    counts = np.tile(
        np.arange(1, N + 1, dtype=np.int32)[:, None], (1, S)
    )  # one append per row per tick
    return ext_t, ext_v, counts, filled0.astype(np.int32), t0, spacing


def _stack_host_inputs(S, N, t0, btc_row=0):
    """(T,)-leading HostInputs matching _make_ext's tick-aligned times."""
    from binquant_tpu.engine.step import default_host_inputs

    per_tick = []
    for t in range(N):
        ts15 = t0 + (t + 1) * 900
        ts5 = t0 + (t + 1) * 300
        per_tick.append(
            default_host_inputs(S)._replace(
                tracked=jnp.ones((S,), bool),
                btc_row=jnp.asarray(btc_row, jnp.int32),
                timestamp_s=jnp.asarray(ts15, jnp.int32),
                timestamp5_s=jnp.asarray(ts5, jnp.int32),
                quiet_hours=jnp.asarray(False),
                grid_policy_allows=jnp.asarray(False),
                is_futures=jnp.asarray(True),
                dominance_is_losers=jnp.asarray(False),
                market_domination_reversal=jnp.asarray(False),
            )
        )
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_tick)


POSITIONAL_PACK_FIELDS = (
    "open_time", "close_time", "open", "high", "low", "close",
    "prev_close", "volume", "quote_volume", "num_trades", "filled", "valid",
)
# cumsum-anchored: equal in exact arithmetic, f32-ulp apart (the anchor
# moves from each view's window start to the series start)
CUMSUM_PACK_FIELDS = (
    "rsi", "mfi", "bb_upper", "bb_mid", "bb_lower", "bb_widths",
    "atr", "atr_ma", "volume_ma",
)
# EWM-carrying: additionally see the pre-window prefix the view path
# truncates — a (1-alpha)^W-scale divergence on rows with > W bars of
# history (must stay WELL inside the 0.25-point declared gate margins)
EWM_PACK_FIELDS = ("rsi_wilder", "macd", "macd_signal", "ema9", "ema21")


def _assert_governed_close(name, a, b, rtol=5e-4, atol=5e-3):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    assert np.array_equal(np.isnan(a), np.isnan(b)), (
        f"{name}: NaN pattern differs"
    )
    m = np.isfinite(a)
    np.testing.assert_allclose(
        a[m], b[m], rtol=rtol, atol=atol, err_msg=name
    )


def test_precompute_ext_parity_synthetic():
    """Tentpole pin: the extension-invariant precompute vs the vmapped
    window-view precompute on a mixed-history synthetic chunk — positional
    fields bit-exact, governed cumsum/EWM fields NaN-pattern-identical and
    numerically tight, BTC positional gathers bit-exact."""
    from binquant_tpu.backtest.kernel import (
        _precompute_ext,
        _precompute_one,
        _window_views,
    )
    from binquant_tpu.strategies.features import ext_gather
    from binquant_tpu.strategies.params import resolve_params

    S, W, N = 8, 120, 12
    ext15_t, ext15_v, counts15, f0_15, t0, _ = _make_ext(
        S, W, N, seed=1, spacing=900, t0=1_780_272_000 - 900
    )
    ext5_t, ext5_v, counts5, f0_5, _, _ = _make_ext(
        S, W, N, seed=2, spacing=300, t0=1_780_272_000 - 300
    )
    # tick-aligned append times: tick t's 15m append is at t0 + (t+1)*900
    inputs_seq = _stack_host_inputs(S, N, 1_780_272_000 - 900, btc_row=0)
    # match the 5m clock to the 5m extension's own base
    inputs_seq = inputs_seq._replace(
        timestamp5_s=jnp.asarray(
            [(1_780_272_000 - 300) + (t + 1) * 300 for t in range(N)],
            jnp.int32,
        )
    )
    sp = resolve_params(None)
    wire_enabled = ("liquidation_sweep_pump",)

    views5 = _window_views(ext5_t, ext5_v, counts5, f0_5, W)
    views15 = _window_views(ext15_t, ext15_v, counts15, f0_15, W)
    ref = jax.vmap(
        lambda b5, b15, inp: _precompute_one(b5, b15, inp, sp)
    )(views5, views15, inputs_seq)

    last5 = (counts5 + (W - 1)).astype(jnp.int32)
    last15 = (counts15 + (W - 1)).astype(jnp.int32)
    got = _precompute_ext(
        (ext5_t, ext5_v), (ext15_t, ext15_v), counts5, counts15,
        (f0_5, f0_15), inputs_seq, sp, W, wire_enabled,
        ext_gather(jnp.asarray(ext5_t), last5),
        ext_gather(jnp.asarray(ext15_t), last15),
        jnp.minimum(f0_5[None, :] + counts5, W).astype(jnp.int32),
        jnp.minimum(f0_15[None, :] + counts15, W).astype(jnp.int32),
    )

    # freshness + fill accounting: bit-exact
    for f in ("fresh5", "fresh15", "filled5", "filled15"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), np.asarray(getattr(got, f)), f
        )
    assert bool(np.asarray(got.fresh15).any())  # gates actually engage

    for pname in ("pack5", "pack15"):
        rp, gp = getattr(ref, pname), getattr(got, pname)
        for f in POSITIONAL_PACK_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(rp, f)), np.asarray(getattr(gp, f)),
                err_msg=f"{pname}.{f} must be bit-exact",
            )
        for f in CUMSUM_PACK_FIELDS:
            _assert_governed_close(
                f"{pname}.{f}", getattr(rp, f), getattr(gp, f)
            )
        for f in EWM_PACK_FIELDS:
            _assert_governed_close(
                f"{pname}.{f}", getattr(rp, f), getattr(gp, f),
                rtol=2e-3, atol=0.15,
            )

    # regime symbol features: positional ints exact, floats governed
    for f in ref.feats15._fields:
        rv, gv = getattr(ref.feats15, f), getattr(got.feats15, f)
        if np.asarray(rv).dtype.kind in "biu":
            np.testing.assert_array_equal(
                np.asarray(rv), np.asarray(gv), err_msg=f"feats15.{f}"
            )
        else:
            # ema20/ema50 carry the EWM prefix divergence
            _assert_governed_close(
                f"feats15.{f}", rv, gv, rtol=2e-3, atol=0.15
            )

    # LSP stays the vmapped kernel in BOTH paths — bit-exact
    for f in (
        "lsp_score_ok", "lsp_trigger_score", "lsp_threshold",
        "lsp_volume_last",
    ):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), np.asarray(getattr(got, f)), f
        )

    # BTC block: beta/corr governed (rolling cumsums), momentum/change_96
    # positional gathers — bit-exact
    _assert_governed_close("btc_beta", ref.btc_beta, got.btc_beta)
    _assert_governed_close("btc_corr", ref.btc_corr, got.btc_corr)
    np.testing.assert_array_equal(
        np.asarray(ref.btc_mom), np.asarray(got.btc_mom), "btc_mom"
    )
    np.testing.assert_array_equal(
        np.asarray(ref.btc_change_96), np.asarray(got.btc_change_96),
        "btc_change_96",
    )


def test_backtest_chunk_ext_governed_fired_sets():
    """The chunk-kernel contract: BQT_EXT_INVARIANT wires may only flip a
    fired set when the tick's margin-proximity digest field sits inside
    the strategy's declared gate margin — outside the band the sets are
    exactly equal. Also pins that the margin fields actually populate."""
    from binquant_tpu.backtest.kernel import backtest_chunk
    from binquant_tpu.engine.step import STRATEGY_ORDER, unpack_wire
    from binquant_tpu.regime.context import ContextConfig
    from binquant_tpu.regime.context import initial_regime_carry
    from binquant_tpu.strategies.params import declared_gate_margins

    S, W, N = 8, 120, 12
    ext15_t, ext15_v, counts15, f0_15, _, _ = _make_ext(
        S, W, N, seed=5, spacing=900, t0=1_780_272_000 - 900
    )
    ext5_t, ext5_v, counts5, f0_5, _, _ = _make_ext(
        S, W, N, seed=6, spacing=300, t0=1_780_272_000 - 300
    )
    inputs_seq = _stack_host_inputs(S, N, 1_780_272_000 - 900, btc_row=0)
    inputs_seq = inputs_seq._replace(
        timestamp5_s=jnp.asarray(
            [(1_780_272_000 - 300) + (t + 1) * 300 for t in range(N)],
            jnp.int32,
        )
    )
    carries = (
        initial_regime_carry(S),
        jnp.full((S,), -1, jnp.int32),
        jnp.full((S,), -1, jnp.int32),
    )
    active = jnp.ones((N,), bool)
    momentum_ok = jnp.ones((N,), bool)
    policy_prev = (jnp.asarray(False), jnp.asarray(-1, jnp.int32))
    args = (
        (jnp.asarray(ext5_t), jnp.asarray(ext5_v)),
        (jnp.asarray(ext15_t), jnp.asarray(ext15_v)),
        jnp.asarray(counts5), jnp.asarray(counts15),
        (jnp.asarray(f0_5), jnp.asarray(f0_15)),
        carries, inputs_seq, active, momentum_ok, policy_prev,
    )
    kwargs = dict(window=W, numeric_digest=True)

    outs = {}
    for ext in (False, True):
        _, _, wires, _, _ = backtest_chunk(
            *args, ContextConfig(), ext_invariant=ext, **kwargs
        )
        outs[ext] = [
            unpack_wire(w, numeric_digest=True) for w in np.asarray(wires)
        ]

    margins = declared_gate_margins()
    from binquant_tpu.engine.step import decode_numeric_digest

    saw_margin_value = False
    for t, ((fr_v, ctx_v), (fr_e, ctx_e)) in enumerate(
        zip(outs[False], outs[True])
    ):
        set_v = set(
            zip(fr_v.strategy_idx.tolist(), fr_v.row.tolist(),
                fr_v.direction.tolist())
        )
        set_e = set(
            zip(fr_e.strategy_idx.tolist(), fr_e.row.tolist(),
                fr_e.direction.tolist())
        )
        dec = decode_numeric_digest(ctx_e["numeric_digest"])
        if any(v is not None for v in dec["margin"].values()):
            saw_margin_value = True
        for sidx, _row, _dirn in set_v ^ set_e:
            name = STRATEGY_ORDER[sidx]
            band = margins.get(name)
            prox = dec["margin"].get(name)
            assert band is not None and prox is not None and prox <= band, (
                f"tick {t}: fired-set flip on {name} outside its declared "
                f"gate margin (proximity={prox}, band={band})"
            )
    assert saw_margin_value  # the digest's margin tail actually populates


def _synthetic_wires(T, S, numeric_digest, ingest_digest, seed=0,
                     overflow_tick=None):
    """Random (T, L) wire blocks shaped like the real layout, with a
    controllable fired count per tick (incl. a > WIRE_MAX_FIRED overflow
    tick) and plausible scalar/calib/digest regions."""
    from binquant_tpu.engine.step import (
        INGEST_DIGEST_WIDTH,
        NUMERIC_DIGEST_WIDTH,
        WIRE_FIRED_COUNT_OFF,
        WIRE_MAX_FIRED,
        wire_length,
    )

    rng = np.random.default_rng(seed)
    L = wire_length(
        S, numeric_digest=numeric_digest, ingest_digest=ingest_digest
    )
    w = rng.random((T, L)).astype(np.float32) * 4.0
    off = WIRE_FIRED_COUNT_OFF
    K = WIRE_MAX_FIRED
    for t in range(T):
        n = int(rng.integers(0, 6))
        if overflow_tick is not None and t == overflow_tick:
            n = K + 7
        w[t, off] = float(n)
        blocks = w[t, off + 1 : off + 1 + 6 * K].reshape(6, K)
        blocks[0] = rng.integers(0, 8, K)  # strategy_idx
        blocks[1] = rng.integers(0, S, K)  # row
    return w


@pytest.mark.parametrize(
    "numeric_digest,ingest_digest",
    [(False, False), (True, False), (True, True)],
)
def test_unpack_wire_block_matches_per_tick(numeric_digest, ingest_digest):
    """Batch decode pin: unpack_wire_block returns exactly the per-tick
    unpack_wire tuples — values, dtypes, overflow flags, digest blocks —
    including through a > WIRE_MAX_FIRED overflow tick."""
    from binquant_tpu.engine.step import unpack_wire, unpack_wire_block

    T, S = 7, 16
    wires = _synthetic_wires(
        T, S, numeric_digest, ingest_digest, seed=3, overflow_tick=4
    )
    batch = unpack_wire_block(
        wires, numeric_digest=numeric_digest, ingest_digest=ingest_digest
    )
    assert len(batch) == T
    for t in range(T):
        ref_fired, ref_ctx = unpack_wire(
            wires[t], numeric_digest=numeric_digest,
            ingest_digest=ingest_digest,
        )
        got_fired, got_ctx = batch[t]
        assert got_fired.n == ref_fired.n
        assert got_fired.overflow == ref_fired.overflow
        for f in ("strategy_idx", "row", "autotrade", "direction",
                  "score", "stop_loss_pct"):
            rv, gv = getattr(ref_fired, f), getattr(got_fired, f)
            assert rv.dtype == gv.dtype, f
            np.testing.assert_array_equal(rv, gv, err_msg=f)
        if ref_fired.payload is None:
            assert got_fired.payload is None
        else:
            np.testing.assert_array_equal(
                ref_fired.payload, got_fired.payload
            )
        assert set(ref_ctx) == set(got_ctx)
        for k, rv in ref_ctx.items():
            gv = got_ctx[k]
            if isinstance(rv, np.ndarray):
                np.testing.assert_array_equal(rv, gv, err_msg=k)
            else:
                assert type(rv) is type(gv), (k, type(rv), type(gv))
                assert rv == gv, k
    assert batch[4][0].overflow  # the engineered overflow tick


def test_margin_digest_unit():
    """Margin-proximity digest unit: engineered packs with known RSI/MFI
    distances must decode to the expected per-strategy minima, NaN (None)
    when no row is eligible, and the regime top1-top2 spread."""
    from binquant_tpu.engine.step import (
        NUMERIC_DIGEST_WIDTH,
        STRATEGY_ORDER,
        _numeric_digest_block,
        decode_numeric_digest,
        numeric_digest_layout,
    )

    layout = numeric_digest_layout()
    assert len(layout) == NUMERIC_DIGEST_WIDTH
    assert layout[-1] == "margin.market_regime"
    for s in STRATEGY_ORDER:
        assert f"margin.{s}" in layout

    S = 4
    n = len(STRATEGY_ORDER)

    class _Pack:
        pass

    def mk_pack(rsi, mfi, rsi_wilder):
        p = _Pack()
        for f in ("close", "volume", "bb_upper", "bb_mid", "bb_lower",
                  "macd", "macd_signal", "atr", "ema9", "ema21"):
            setattr(p, f, jnp.ones((S,), jnp.float32))
        p.rsi = jnp.asarray(rsi, jnp.float32)
        p.mfi = jnp.asarray(mfi, jnp.float32)
        p.rsi_wilder = jnp.asarray(rsi_wilder, jnp.float32)
        return p

    class _Summary:
        score = jnp.ones((n, S), jnp.float32)
        stop_loss_pct = jnp.ones((n, S), jnp.float32)
        trigger = jnp.zeros((n, S), bool)

    class _Ctx:
        long_regime_score = jnp.asarray(0.7, jnp.float32)
        short_regime_score = jnp.asarray(0.1, jnp.float32)
        range_regime_score = jnp.asarray(0.5, jnp.float32)
        stress_regime_score = jnp.asarray(0.2, jnp.float32)

    ones = jnp.ones((S,), bool)
    # PT margin: defaults rsi_oversold=30 / mfi_oversold=20 → min distance
    # over rows = min(|31-30|, |28.5-30|, |26-20|, ...) = 1.0 vs mfi row 1
    # at |19.8-20| = 0.2
    pack5 = mk_pack(
        rsi=[31.0, 50.0, 60.0, 70.0],
        mfi=[40.0, 19.8, 60.0, 70.0],
        rsi_wilder=[50.0] * S,
    )
    # MRF margin: thresholds 25/75 → closest is |71-75| = 4
    pack15 = mk_pack(
        rsi=[50.0] * S, mfi=[50.0] * S,
        rsi_wilder=[50.0, 60.0, 71.0, 40.0],
    )
    block = _numeric_digest_block(
        pack5, pack15, _Summary(), jnp.zeros((S,)), jnp.zeros((S,)),
        ones, ones, ones, ones, ones, jnp.zeros((S,), bool),
        wire_fields_only=True, sp=None, context=_Ctx(),
    )
    dec = decode_numeric_digest(np.asarray(block))
    m = dec["margin"]
    assert m["coinrule_price_tracker"] == pytest.approx(0.2, abs=1e-5)
    assert m["mean_reversion_fade"] == pytest.approx(4.0, abs=1e-5)
    # IPT gates on the same 30/20 baked constants → same 0.2 proximity
    assert m["inverse_price_tracker"] == pytest.approx(0.2, abs=1e-5)
    # undeclared strategies stay None
    assert m["activity_burst_pump"] is None
    assert m["grid_ladder"] is None
    assert m["market_regime"] == pytest.approx(0.2, abs=1e-5)  # 0.7 - 0.5

    # no eligible rows → every margin decodes None
    zeros = jnp.zeros((S,), bool)
    block2 = _numeric_digest_block(
        pack5, pack15, _Summary(), jnp.zeros((S,)), jnp.zeros((S,)),
        zeros, zeros, zeros, zeros, zeros, jnp.zeros((S,), bool),
        wire_fields_only=True, sp=None, context=None,
    )
    dec2 = decode_numeric_digest(np.asarray(block2))
    assert all(v is None for v in dec2["margin"].values())


def test_auto_sweep_chunk_derivation():
    """Sweep memory-budget satellite: huge grids drop the chunk to fit the
    P x S x 80 x 4B dominant term; small grids keep the configured chunk;
    the floor is 1."""
    from binquant_tpu.backtest.driver import _auto_sweep_chunk

    # small grid: untouched
    assert _auto_sweep_chunk(16, 4, 64, 1024) == 16
    # huge grid: P*S*320B = 4096*512*320 = 671 MB/tick → 1 tick fits
    assert _auto_sweep_chunk(16, 4096, 512, 1024) == 1
    # mid grid scales between
    mid = _auto_sweep_chunk(64, 256, 256, 1024)
    assert 1 <= mid <= 64
    assert mid == min(64, (1024 << 20) // (256 * 256 * 320))
    # floor at 1 even when the budget is smaller than one tick
    assert _auto_sweep_chunk(16, 10_000, 4096, 64) == 1


@pytest.mark.slow
def test_backtest_ext_end_to_end_set_equality(tmp_path):
    """End-to-end governed pin: on a generated replay stream the
    BQT_EXT_INVARIANT drive's emitted signal set equals the default
    vmapped drive's (any legal divergence must hide inside declared gate
    margins — none does on this stream), and the chunks actually batched.

    Slow-marked (two full replay drives): runs via ``make backtest-smoke``
    next to the PR 6 fixture/overflow/rewrite drills."""
    from binquant_tpu.backtest import run_backtest
    from binquant_tpu.io.replay import generate_replay_file

    path = tmp_path / "ext.jsonl"
    generate_replay_file(path, n_symbols=16, n_ticks=112)
    default: list = []
    d_stats = run_backtest(
        path, capacity=32, window=120, collect=default, chunk=16,
    )
    ext: list = []
    e_stats = run_backtest(
        path, capacity=32, window=120, collect=ext, chunk=16,
        ext_invariant=True,
    )
    assert set(default) == set(ext), {
        "only_default": sorted(set(default) - set(ext))[:5],
        "only_ext": sorted(set(ext) - set(default))[:5],
    }
    assert len(default) > 0
    assert e_stats["backtest_chunks"] >= 2
    assert e_stats["ticks"] == d_stats["ticks"]
