"""End-to-end wire-overflow drill (VERDICT r3 item 7).

The wire compacts fired (strategy, row) pairs into WIRE_MAX_FIRED=128
slots; a market-wide crash can legitimately fire MeanReversionFade on
more symbols than that in ONE tick. This drives >128 simultaneous fires
through the full dispatch→emission path and proves:

* the overflow fallback emits the IDENTICAL signal set the uncapped
  pandas oracle derives (nothing dropped, nothing duplicated);
* the engine actually took the fallback path (not a quietly-widened wire);
* the latency cliff is measured, not guessed (overflow_p99_ms in stats).
"""

from __future__ import annotations

import pytest

from binquant_tpu.engine.step import WIRE_MAX_FIRED
from binquant_tpu.io.replay import generate_burst_replay, run_replay_ab

N_SYMBOLS = 160  # > WIRE_MAX_FIRED so the burst must overflow


@pytest.mark.slow
def test_overflow_burst_emits_identical_set(tmp_path):
    assert N_SYMBOLS > WIRE_MAX_FIRED
    path = tmp_path / "burst.jsonl"
    generate_burst_replay(path, n_symbols=N_SYMBOLS, n_ticks=108)

    result = run_replay_ab(path, capacity=256, window=200)

    # the burst actually overflowed the wire, exercising the fallback
    stats = result["tpu_stats"]
    assert stats["overflow_ticks"] >= 1, "burst never overflowed the wire"
    assert stats["overflow_p99_ms"] is not None  # the cliff is measured

    # identical signal set vs the uncapped oracle — the fallback lost
    # nothing past slot 128
    assert result["match"], {
        "only_tpu": result["only_tpu"][:5],
        "only_oracle": result["only_oracle"][:5],
    }
    mrf = [
        s for s in result["strategies"] if s == "mean_reversion_fade"
    ]
    assert mrf, "the crash tick must fire MeanReversionFade"
    # ONE tick fired more pairs than the wire holds (not just the session)
    assert result["per_tick_max"] > WIRE_MAX_FIRED


@pytest.mark.slow
def test_overflow_burst_through_donated_incremental_path(tmp_path):
    """ISSUE 4's hardest corner: the SAME >128-fire burst through the
    production default pair — incremental strategy carries + DONATED
    dispatch. The overflow fallback here cannot touch the pre-tick buffers
    (donated); it re-evaluates from the post-tick state + the small-carry
    snapshots. The emitted set must still match the uncapped oracle
    signal-for-signal."""
    path = tmp_path / "burst_donated.jsonl"
    generate_burst_replay(path, n_symbols=N_SYMBOLS, n_ticks=108)

    result = run_replay_ab(
        path, capacity=256, window=200, incremental=True, donate=True
    )
    stats = result["tpu_stats"]
    assert stats["overflow_ticks"] >= 1, "burst never overflowed the wire"
    assert stats["donated_ticks"] > 0
    assert stats["donated_state_resets"] == 0
    assert stats["incremental_ticks"] > 0
    assert result["match"], {
        "only_tpu": result["only_tpu"][:5],
        "only_oracle": result["only_oracle"][:5],
    }
    assert result["per_tick_max"] > WIRE_MAX_FIRED
