"""Technical-indicator kernels, batched along the last axis.

Replaces the reference's per-symbol pandas pipeline: pybinbot ``Indicators``
(moving_averages/macd/rsi/mfi/ma_spreads/bollinguer_spreads/set_twap/atr/
set_supertrend — consumed at ``/root/reference/producers/context_evaluator.py:237-249``)
plus the strategies' inline kernels (Wilder RSI at
``strategies/mean_reversion_fade.py:79-100``, ADX at
``strategies/range_bb_rsi_mean_reversion.py:100-129``, Connors RSI at
``strategies/coinrule/bb_extreme_reversion.py``).

All functions take/return ``(..., W)`` arrays; a batched ``(S, W)`` market
buffer flows through with no vmap. NaN marks warm-up, as in pandas.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from binquant_tpu.ops.rolling import (
    diff,
    ewm_mean,
    rolling_mean,
    rolling_std,
    rolling_sum,
    rolling_var,
    shift,
)
from binquant_tpu.utils import jsafe_div

__all__ = [
    "sma",
    "ema",
    "true_range",
    "atr",
    "atr_wilder",
    "rsi_wilder",
    "rsi_sma",
    "macd",
    "mfi",
    "bollinger",
    "twap",
    "typical_price",
    "supertrend",
    "adx",
    "connors_rsi",
    "zscore",
    "rolling_beta_corr",
    "log_returns",
    "ma_spreads",
    "bb_spreads",
]


def sma(close: jnp.ndarray, window: int, min_periods: int | None = None) -> jnp.ndarray:
    return rolling_mean(close, window, min_periods)


def ema(close: jnp.ndarray, span: float, min_periods: int = 1) -> jnp.ndarray:
    return ewm_mean(close, span=span, min_periods=min_periods)


def typical_price(high: jnp.ndarray, low: jnp.ndarray, close: jnp.ndarray) -> jnp.ndarray:
    return (high + low + close) / 3.0


def true_range(
    high: jnp.ndarray, low: jnp.ndarray, close: jnp.ndarray
) -> jnp.ndarray:
    """max(h-l, |h-prev_c|, |l-prev_c|); first bar falls back to h-l."""
    prev_close = shift(close, 1)
    hl = high - low
    hc = jnp.abs(high - prev_close)
    lc = jnp.abs(low - prev_close)
    tr = jnp.maximum(hl, jnp.maximum(hc, lc))
    return jnp.where(jnp.isfinite(prev_close), tr, hl)


def atr(
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    window: int = 14,
    min_periods: int | None = None,
) -> jnp.ndarray:
    """SMA-of-true-range ATR (the variant the reference's market context uses:
    ``live_market_context_accumulator.py:268``)."""
    return rolling_mean(true_range(high, low, close), window, min_periods)


def atr_wilder(
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    window: int = 14,
) -> jnp.ndarray:
    """Wilder-smoothed ATR (ewm alpha=1/window)."""
    return ewm_mean(true_range(high, low, close), alpha=1.0 / window, min_periods=window)


def rsi_wilder(close: jnp.ndarray, window: int = 14) -> jnp.ndarray:
    """Wilder/EWM RSI; 100*avg_gain/(avg_gain+avg_loss) with a 50.0 flat-case
    override, matching the backtested variant at
    ``strategies/mean_reversion_fade.py:79-100``."""
    delta = diff(close, 1)
    gain = jnp.maximum(delta, 0.0)
    loss = jnp.maximum(-delta, 0.0)
    a = 1.0 / window
    avg_gain = ewm_mean(gain, alpha=a, min_periods=window)
    avg_loss = ewm_mean(loss, alpha=a, min_periods=window)
    denom = avg_gain + avg_loss
    out = jnp.where(denom != 0, 100.0 * avg_gain / jnp.where(denom != 0, denom, 1.0), 50.0)
    return jnp.where(jnp.isfinite(avg_gain) & jnp.isfinite(avg_loss), out, jnp.nan)


def rsi_sma(close: jnp.ndarray, window: int = 14) -> jnp.ndarray:
    """Simple-rolling-mean RSI (the pybinbot Indicators.rsi variant — the
    mean_reversion_fade docstring pins the difference)."""
    delta = diff(close, 1)
    gain = jnp.maximum(delta, 0.0)
    loss = jnp.maximum(-delta, 0.0)
    avg_gain = rolling_mean(gain, window)
    avg_loss = rolling_mean(loss, window)
    denom = avg_gain + avg_loss
    out = jnp.where(denom != 0, 100.0 * avg_gain / jnp.where(denom != 0, denom, 1.0), 50.0)
    return jnp.where(jnp.isfinite(avg_gain) & jnp.isfinite(avg_loss), out, jnp.nan)


class MACDResult(NamedTuple):
    macd: jnp.ndarray
    signal: jnp.ndarray
    histogram: jnp.ndarray


def macd(
    close: jnp.ndarray, fast: int = 12, slow: int = 26, signal: int = 9
) -> MACDResult:
    line = ema(close, fast) - ema(close, slow)
    sig = ewm_mean(line, span=signal)
    return MACDResult(line, sig, line - sig)


def mfi(
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    volume: jnp.ndarray,
    window: int = 14,
) -> jnp.ndarray:
    tp = typical_price(high, low, close)
    flow = tp * volume
    up = diff(tp, 1) > 0
    down = diff(tp, 1) < 0
    pos = rolling_sum(jnp.where(up, flow, 0.0), window)
    neg = rolling_sum(jnp.where(down, flow, 0.0), window)
    total = pos + neg
    out = jnp.where(total != 0, 100.0 * pos / jnp.where(total != 0, total, 1.0), 50.0)
    return jnp.where(jnp.isfinite(pos) & jnp.isfinite(neg), out, jnp.nan)


class BollingerResult(NamedTuple):
    upper: jnp.ndarray
    mid: jnp.ndarray
    lower: jnp.ndarray


def bollinger(
    close: jnp.ndarray,
    window: int = 20,
    num_std: float = 2.0,
    min_periods: int | None = None,
    ddof: int = 0,
) -> BollingerResult:
    mid = rolling_mean(close, window, min_periods)
    sd = rolling_std(close, window, min_periods, ddof=ddof)
    sd = jnp.where(jnp.isfinite(sd), sd, 0.0)
    return BollingerResult(mid + num_std * sd, mid, mid - num_std * sd)


def twap(
    open_: jnp.ndarray,
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    window: int = 20,
) -> jnp.ndarray:
    """Rolling time-weighted average price over OHLC bar means."""
    bar_avg = (open_ + high + low + close) / 4.0
    return rolling_mean(bar_avg, window, min_periods=1)


class SupertrendResult(NamedTuple):
    supertrend: jnp.ndarray
    direction: jnp.ndarray  # +1 uptrend, -1 downtrend


def _supertrend_step(
    carry: tuple,
    hb: jnp.ndarray,
    lb_: jnp.ndarray,
    cb: jnp.ndarray,
    active: jnp.ndarray,
    window: int,
    multiplier: float,
) -> tuple[tuple, jnp.ndarray, jnp.ndarray]:
    """ONE bar of the path-dependent supertrend recursion, elementwise over
    any lane shape. The single copy shared by the full-window scan below
    and the incremental carry (``ops/incremental.py:supertrend_advance``).
    Returns (carry', line, direction) with outputs NaN until the ATR
    recursion is warm."""
    atr, n_seen, fu, fl, d, prev_close = carry
    alpha = 1.0 / window
    hl2 = (hb + lb_) / 2.0
    tr_first = hb - lb_
    tr = jnp.where(
        n_seen == 0,
        tr_first,
        jnp.maximum(
            tr_first,
            jnp.maximum(jnp.abs(hb - prev_close), jnp.abs(lb_ - prev_close)),
        ),
    )
    atr_new = jnp.where(n_seen == 0, tr, atr + alpha * (tr - atr))
    n_new = n_seen + 1
    atr_ready = n_new >= window
    ub = jnp.where(atr_ready, hl2 + multiplier * atr_new, jnp.inf)
    lb = jnp.where(atr_ready, hl2 - multiplier * atr_new, -jnp.inf)
    fu_new = jnp.where((ub < fu) | (prev_close > fu), ub, fu)
    fl_new = jnp.where((lb > fl) | (prev_close < fl), lb, fl)
    d_new = jnp.where(cb > fu_new, 1.0, jnp.where(cb < fl_new, -1.0, d))
    # inactive lanes (before their start) keep the initial carry
    keep = lambda new, old: jnp.where(active, new, old)
    new_carry = (
        keep(atr_new, atr),
        keep(n_new, n_seen).astype(jnp.int32),
        keep(fu_new, fu),
        keep(fl_new, fl),
        keep(d_new, d),
        keep(cb, prev_close),
    )
    line = jnp.where(d_new > 0, fl_new, fu_new)
    # a mid-series NaN bar poisons the ATR recursion permanently (the
    # pandas mirror dropna()s such rows away entirely); masking on ATR
    # finiteness keeps the output NaN from the gap onward instead of
    # serving frozen pre-gap bands as live values
    valid = active & atr_ready & jnp.isfinite(atr_new)
    return (
        new_carry,
        jnp.where(valid, line, jnp.nan),
        jnp.where(valid, d_new, jnp.nan),
    )


def supertrend_scan_init(batch_shape: tuple[int, ...]) -> tuple:
    """The recursion's initial carry (atr, n_seen, final_upper,
    final_lower, direction, prev_close) — the ONE source shared by the
    full-window scan below and ``ops.incremental``'s empty-carry
    constructor (``SupertrendCarry`` leaf order/dtypes/values must match
    this tuple exactly). Every float leaf is EXPLICITLY f32: an inferred
    (weak) dtype here would give a carry-holding EngineState different jit
    avals than its checkpoint-restored twin (np round-trips come back
    strong), and every restart with a checkpoint would silently pay a
    second full wire compile."""
    return (
        jnp.zeros(batch_shape, dtype=jnp.float32),
        jnp.zeros(batch_shape, dtype=jnp.int32),
        jnp.full(batch_shape, jnp.inf, dtype=jnp.float32),
        jnp.full(batch_shape, -jnp.inf, dtype=jnp.float32),
        jnp.ones(batch_shape, dtype=jnp.float32),
        jnp.zeros(batch_shape, dtype=jnp.float32),
    )


def _supertrend_scan(
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    start: jnp.ndarray,
    window: int,
    multiplier: float,
) -> tuple[tuple, jnp.ndarray, jnp.ndarray]:
    """Scan the recursion over the window; returns the FINAL carry (each
    leaf reshaped to the lane batch — the seed for incremental advance)
    plus the full (…, W) line/direction series."""
    import jax

    W = close.shape[-1]
    batch_shape = close.shape[:-1]
    flat = lambda z: jnp.reshape(z, (-1, W)).T  # (W, B)
    h, lo, c = flat(high), flat(low), flat(close)
    start_b = jnp.reshape(jnp.broadcast_to(start, batch_shape), (-1,))
    B = c.shape[1]

    def step(carry, inputs):
        hb, lb_, cb, idx = inputs
        new_carry, line, dirn = _supertrend_step(
            carry, hb, lb_, cb, idx >= start_b, window, multiplier
        )
        return new_carry, (line, dirn)

    init = supertrend_scan_init((B,))
    final, (st, dirn) = jax.lax.scan(
        step, init, (h, lo, c, jnp.arange(W, dtype=jnp.int32))
    )
    unflat = lambda z: jnp.reshape(z.T, batch_shape + (W,))
    final = tuple(jnp.reshape(leaf, batch_shape) for leaf in final)
    return final, unflat(st), unflat(dirn)


def supertrend_from(
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    start: jnp.ndarray,
    window: int = 10,
    multiplier: float = 3.0,
) -> SupertrendResult:
    """Supertrend whose series BEGINS at per-lane index ``start``.

    The reference computes supertrend on a dropna'd frame
    (``coinrule.py:140-143`` after ``pre_process``), i.e. the series'
    first bar is the first row surviving the enrichment warm-up — and the
    ratchet + Wilder-ATR recursion are path-dependent, so seeding from
    the full window would diverge. TR, the ATR recursion (ewm
    ``adjust=False``, NaN before ``window`` samples) and the band ratchet
    all restart at ``start``: bars before it are ignored entirely,
    matching ``Indicators.set_supertrend`` applied to ``df.iloc[s:]``.
    """
    _, st, dirn = _supertrend_scan(high, low, close, start, window, multiplier)
    return SupertrendResult(st, dirn)


def supertrend(
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    window: int = 10,
    multiplier: float = 3.0,
) -> SupertrendResult:
    """Supertrend over the full series: :func:`supertrend_from` started at
    each lane's first finite bar (ring buffers left-pad unfilled lanes
    with NaN). One copy of the path-dependent ratchet recursion lives in
    ``supertrend_from``; numeric parity vs the sequential pandas mirror is
    pinned in tests/test_ops_parity.py (test_supertrend_matches_pandas),
    trend-flip behavior in test_supertrend_flips_with_trend."""
    W = close.shape[-1]
    finite = jnp.isfinite(high) & jnp.isfinite(low) & jnp.isfinite(close)
    start = jnp.min(
        jnp.where(finite, jnp.arange(W, dtype=jnp.int32), W), axis=-1
    )
    return supertrend_from(high, low, close, start, window, multiplier)


def adx(
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    window: int = 14,
) -> jnp.ndarray:
    """Wilder ADX from +DM/−DM/TR ewm smoothing."""
    up_move = diff(high, 1)
    down_move = -diff(low, 1)
    plus_dm = jnp.where((up_move > down_move) & (up_move > 0), up_move, 0.0)
    minus_dm = jnp.where((down_move > up_move) & (down_move > 0), down_move, 0.0)
    a = 1.0 / window
    tr_s = ewm_mean(true_range(high, low, close), alpha=a, min_periods=window)
    plus_di = 100.0 * jsafe_div(ewm_mean(plus_dm, alpha=a, min_periods=window), tr_s)
    minus_di = 100.0 * jsafe_div(ewm_mean(minus_dm, alpha=a, min_periods=window), tr_s)
    dx = 100.0 * jsafe_div(jnp.abs(plus_di - minus_di), plus_di + minus_di)
    dx = jnp.where(jnp.isfinite(tr_s), dx, jnp.nan)
    return ewm_mean(dx, alpha=a, min_periods=window)


def _percent_rank(x: jnp.ndarray, window: int) -> jnp.ndarray:
    """Percent of the previous `window` values strictly below the current."""
    from binquant_tpu.ops.rolling import _windowed_view

    win = _windowed_view(shift(x, 1), window)
    cur = x[..., None]
    below = jnp.sum(jnp.where(jnp.isfinite(win), (win < cur).astype(x.dtype), 0.0), axis=-1)
    cnt = jnp.sum(jnp.isfinite(win), axis=-1)
    return jnp.where(cnt >= window, 100.0 * below / jnp.maximum(cnt, 1), jnp.nan)


def connors_rsi(
    close: jnp.ndarray,
    rsi_window: int = 3,
    streak_window: int = 2,
    rank_window: int = 100,
) -> jnp.ndarray:
    """Connors RSI = mean(RSI(close,3), RSI(streak,2), PercentRank(ret,100))."""
    d = diff(close, 1)
    sign = jnp.sign(d)
    # streak: consecutive same-sign run length, signed — sequential, via scan
    import jax

    W = close.shape[-1]
    flat_sign = jnp.reshape(sign, (-1, W)).T

    def step(carry, s):
        streak = jnp.where(
            s > 0,
            jnp.where(carry > 0, carry + 1, 1.0),
            jnp.where(s < 0, jnp.where(carry < 0, carry - 1, -1.0), 0.0),
        )
        return streak, streak

    _, streaks = jax.lax.scan(step, jnp.zeros((flat_sign.shape[1],)), flat_sign)
    streak = jnp.reshape(streaks.T, close.shape)
    ret = jsafe_div(d, shift(close, 1))
    r1 = rsi_wilder(close, rsi_window)
    r2 = rsi_wilder(streak, streak_window)
    r3 = _percent_rank(ret, rank_window)
    return (r1 + r2 + r3) / 3.0


def zscore(x: jnp.ndarray, window: int = 20, ddof: int = 0) -> jnp.ndarray:
    mu = rolling_mean(x, window)
    sd = rolling_std(x, window, ddof=ddof)
    return jsafe_div(x - mu, sd)


def log_returns(close: jnp.ndarray) -> jnp.ndarray:
    prev = shift(close, 1)
    ok = (close > 0) & (prev > 0)
    return jnp.where(ok, jnp.log(jnp.where(ok, close / jnp.where(prev > 0, prev, 1.0), 1.0)), jnp.nan)


class BetaCorrResult(NamedTuple):
    beta: jnp.ndarray
    corr: jnp.ndarray


def rolling_beta_corr(
    asset_returns: jnp.ndarray,
    bench_returns: jnp.ndarray,
    window: int = 50,
) -> BetaCorrResult:
    """Rolling OLS beta and Pearson correlation of asset vs benchmark returns
    (reference ``producers/context_evaluator.py:144-184``). `bench_returns`
    broadcasts against the leading axes of `asset_returns`."""
    b = jnp.broadcast_to(bench_returns, asset_returns.shape)
    both = jnp.isfinite(asset_returns) & jnp.isfinite(b)
    x = jnp.where(both, asset_returns, jnp.nan)
    y = jnp.where(both, b, jnp.nan)
    mx = rolling_mean(x, window)
    my = rolling_mean(y, window)
    mxy = rolling_mean(x * y, window)
    myy = rolling_mean(y * y, window)
    vx = rolling_var(x, window, ddof=0)
    cov = mxy - mx * my
    var_b = myy - my * my
    beta = jsafe_div(cov, var_b)
    corr = jsafe_div(cov, jnp.sqrt(jnp.maximum(vx * var_b, 0.0)))
    return BetaCorrResult(beta, jnp.clip(corr, -1.0, 1.0))


class MASpreads(NamedTuple):
    ma_7_25: jnp.ndarray
    ma_25_100: jnp.ndarray
    ma_7_100: jnp.ndarray


def ma_spreads(close: jnp.ndarray) -> MASpreads:
    """Relative spreads between the 7/25/100 moving averages."""
    ma7 = rolling_mean(close, 7, min_periods=1)
    ma25 = rolling_mean(close, 25, min_periods=1)
    ma100 = rolling_mean(close, 100, min_periods=1)
    return MASpreads(
        jsafe_div(ma7 - ma25, ma25),
        jsafe_div(ma25 - ma100, ma100),
        jsafe_div(ma7 - ma100, ma100),
    )


class BBSpreads(NamedTuple):
    band_spread: jnp.ndarray  # (upper-lower)/mid
    top_spread: jnp.ndarray  # (upper-mid)/mid
    bottom_spread: jnp.ndarray  # (mid-lower)/mid


def bb_spreads(bb: BollingerResult) -> BBSpreads:
    return BBSpreads(
        jsafe_div(bb.upper - bb.lower, bb.mid),
        jsafe_div(bb.upper - bb.mid, bb.mid),
        jsafe_div(bb.mid - bb.lower, bb.mid),
    )
