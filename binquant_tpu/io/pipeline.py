"""The signal engine pipeline: ingest → device tick → emission.

Equivalent of ``/root/reference/consumers/klines_provider.py`` +
``/root/reference/main.py``, inverted TPU-first (SURVEY.md §7): instead of
per-message REST refetch + per-symbol pandas, candles accumulate in the
IngestBatcher between ticks and ONE jit'd ``tick_step`` evaluates the whole
market; the host then emits only fired rows. Periodic jobs keep the
reference's cadence: market breadth + leverage calibration once per 15m
bucket (klines_provider.py:244-250,305-319), KuCoin OI with a 5 s TTL cache
(l.252-276), heartbeat after each processed tick (main.py:30-32,53).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import math
import time
from collections import deque
try:  # py3.11+
    from datetime import UTC, datetime
except ImportError:  # py3.10: datetime.UTC not there yet
    from datetime import datetime, timezone

    UTC = timezone.utc
from pathlib import Path
from typing import Any, NamedTuple

import numpy as np

from binquant_tpu.config import Config
from binquant_tpu.engine.buffer import IngestBatcher, SymbolRegistry
from binquant_tpu.engine.step import (
    MIN_INCR_ENGINE_WINDOW,
    WIRE_FIRED_COUNT_OFF,
    WIRE_MAX_FIRED,
    apply_updates_carry_step,
    apply_updates_carry_step_counted,
    apply_updates_scan,
    apply_updates_scan_counted,
    apply_updates_step,
    apply_updates_step_counted,
    default_host_inputs,
    initial_engine_state,
    measure_carry_drift,
    observe_dispatch,
    pad_updates,
    tick_step,
    tick_step_scan,
    tick_step_wire,
    tick_step_wire_donated,
    unpack_wire,
)
from binquant_tpu.io.autotrade import AutotradeConsumer
from binquant_tpu.io.binbot import BinbotApi
from binquant_tpu.io.emission import (
    FIVE_MIN_STRATEGIES,
    LIVE_STRATEGIES,
    dispatch_signal_record,
    extract_fired,
)
from binquant_tpu.io.leverage import LeverageCalibrator
from binquant_tpu.io.metrics import LatencyTracker
from binquant_tpu.io.telegram import TelegramConsumer
from binquant_tpu.obs.events import get_event_log
from binquant_tpu.obs.instruments import (
    BC_DIRTY_ROWS,
    FULL_RECOMPUTE,
    HEARTBEAT_FAILURES,
    OVERFLOW_TICKS,
    QUEUE_DEPTH,
    SCAN_CHUNKS,
    SCAN_OVERFLOW_RERUNS,
    SCANNED_TICKS,
    SIGNALS,
    TICKS,
)
from binquant_tpu.obs.ingest import IngestHealthMonitor
from binquant_tpu.obs.latency import FreshnessTracker, PhaseAccountant
from binquant_tpu.obs.ledger import LEDGER, abstract_args, lowered_cost
from binquant_tpu.obs.numeric import DriftMeter, NumericHealthMonitor
from binquant_tpu.obs.outcomes import OutcomeTracker
from binquant_tpu.obs.tracing import (
    NULL_TRACE,
    Tracer,
    profiler_window_active,
    step_annotation,
)
from binquant_tpu.regime.context import ContextConfig
from binquant_tpu.regime.grid_policy import GridOnlyPolicy
from binquant_tpu.regime.time_filter import is_quiet_hours
from binquant_tpu.schemas import MarketBreadthSeries
from binquant_tpu.strategies.market_regime_notifier import MarketRegimeNotifier

FIFTEEN_MIN_S = 900
FIVE_MIN_S = 300


def breadth_scalars(
    mb: MarketBreadthSeries | None,
) -> tuple[float, float, float, float, float]:
    """(adp_latest, adp_prev, adp_diff, adp_diff_prev, momentum_points)
    from a market-breadth series. Module-level so the replay oracle
    mirrors the live pipeline's resolution exactly (one copy of the
    semantics — the A/B harness validates against THIS function)."""
    nan = float("nan")
    if mb is None or len(mb.timestamp) < 2:
        return nan, nan, nan, nan, nan
    # the live API may null individual entries (model tolerates them);
    # treat None as NaN rather than crashing the tick input build
    values = [nan if v is None else float(v) for v in mb.market_breadth]
    adp_latest = values[-1] if values else nan
    adp_prev = values[-2] if len(values) >= 2 else nan
    adp_diff = values[-1] - values[-2] if len(values) >= 2 else nan
    adp_diff_prev = values[-2] - values[-3] if len(values) >= 3 else nan
    # momentum prefers the smoothed MA series; nulled/non-finite entries
    # are dropped (not propagated as NaN) so the raw-values fallback
    # engages exactly when the MA series is unusable — the same
    # preference order grid_policy's reading applies
    ma = [
        float(v)
        for v in mb.market_breadth_ma
        if v is not None and math.isfinite(float(v))
    ]
    momentum = (ma[-1] - ma[-2]) * 100 if len(ma) >= 2 else (
        (values[-1] - values[-2]) * 100 if len(values) >= 2 else nan
    )
    return adp_latest, adp_prev, adp_diff, adp_diff_prev, momentum


class OpenInterestCache:
    """KuCoin OI growth per symbol, refreshed by a BACKGROUND task.

    The reference fetches OI inline per incoming message with a 5 s TTL
    (klines_provider.py:252-276) — tolerable at one message at a time, but
    the batched engine sees every fresh symbol in ONE tick; a synchronous
    GET per symbol inside ``process_tick`` would hold the event loop for up
    to N round trips at a 15m boundary. Here the tick path is read-only:
    :meth:`growth` returns the last growth computed by the background
    :meth:`refresh_forever` loop, which walks the tracked universe in
    bounded-concurrency batches amortized across the bucket.
    """

    def __init__(
        self,
        futures_api: Any | None,
        max_concurrency: int = 8,
        batch_size: int = 40,
        batch_interval_s: float = 1.0,
        growth_horizon_s: float = 900.0,
        stale_after_s: float = 300.0,
    ) -> None:
        self.futures_api = futures_api
        self.max_concurrency = max_concurrency
        self.batch_size = batch_size
        self.batch_interval_s = batch_interval_s
        # Growth is measured against the newest sample at least this old —
        # matching the reference's cadence, where the previous OI reading
        # came with the previous fresh 15m candle (~900 s earlier). A
        # sweep-to-sweep ratio (~50 s apart at 2000 symbols) would almost
        # never clear LSP's >=1.02 confirmation gate and quietly veto the
        # whole strategy.
        self.growth_horizon_s = growth_horizon_s
        # A growth value not refreshed within this window decays to NaN —
        # the reference's TTL'd cache never served stale OI after the
        # endpoint started failing; neither may this one (a cached 1.05
        # would keep passing LSP's confirmation gate on dead data).
        self.stale_after_s = stale_after_s
        self._growth: dict[str, tuple[float, float]] = {}  # sym -> (ts, ratio)
        self._samples: dict[str, deque[tuple[float, float]]] = {}
        self._cursor = 0
        self.requests_made = 0

    @property
    def has_data(self) -> bool:
        return bool(self._growth)

    def growth(self, symbol: str) -> float:
        """Cache-only read (the tick path performs ZERO REST calls): OI
        now / the >=horizon-old background sample; NaN when unsampled or
        stale (fetches failing)."""
        entry = self._growth.get(symbol)
        if entry is None or time.monotonic() - entry[0] > self.stale_after_s:
            return float("nan")
        return entry[1]

    async def refresh_batch(self, symbols: list[str]) -> None:
        """Fetch OI for ``symbols`` with bounded concurrency; growth is the
        ratio of the new sample to the newest sample at least
        ``growth_horizon_s`` old (NaN until such a baseline exists)."""
        if self.futures_api is None or not symbols:
            return
        sem = asyncio.Semaphore(self.max_concurrency)

        async def one(symbol: str) -> None:
            async with sem:
                try:
                    oi = float(
                        await asyncio.to_thread(
                            self.futures_api.get_open_interest, symbol
                        )
                    )
                except Exception:
                    return
                self.requests_made += 1
                now = time.monotonic()
                dq = self._samples.setdefault(symbol, deque())
                # baseline BEFORE appending: the newest sample older than
                # the horizon (horizon 0 degenerates to "previous sample")
                cutoff = now - self.growth_horizon_s
                while len(dq) > 1 and dq[1][0] <= cutoff:
                    dq.popleft()
                baseline = dq[0] if dq and dq[0][0] <= cutoff else None
                dq.append((now, oi))
                if baseline is not None and baseline[1] > 0:
                    self._growth[symbol] = (now, oi / baseline[1])

        await asyncio.gather(*(one(s) for s in symbols))

    async def refresh_forever(self, symbols_fn) -> None:
        """Background loop: rotate through ``symbols_fn()`` one batch per
        interval. At 2000 symbols / 40 per second a full sweep takes ~50 s —
        well inside a 15m bucket, and never on the tick path."""
        if self.futures_api is None:
            return
        while True:
            try:
                names = symbols_fn()
                if names:
                    if self._cursor >= len(names):
                        self._cursor = 0
                        # sweep wrap: drop state for symbols that left the
                        # tracked universe so churn can't grow the caches
                        # without bound
                        keep = set(names)
                        for stale in [
                            s for s in self._samples if s not in keep
                        ]:
                            self._samples.pop(stale, None)
                            self._growth.pop(stale, None)
                    batch = names[self._cursor : self._cursor + self.batch_size]
                    self._cursor += self.batch_size
                    await self.refresh_batch(batch)
            except asyncio.CancelledError:
                raise
            except Exception:
                logging.exception("OI refresh batch failed; continuing")
            await asyncio.sleep(self.batch_interval_s)


_copy_small_carries = None


def _unique_buffers(state):
    """``state`` with any leaf that SHARES a device buffer with an earlier
    leaf replaced by a fresh copy. Donating a pytree whose leaves alias
    one buffer (identical zero-fills in a fresh state; XLA deduping two
    identical outputs of a step into one buffer) makes the runtime raise
    "Attempt to donate the same buffer twice" — the double-buffered
    dispatch runs its scratch slot through here first. Pointer reads are
    ~free; only genuinely-aliased (small) leaves pay a copy."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(state)
    seen: set[int] = set()
    out = []
    for leaf in leaves:
        try:
            ptr = leaf.unsafe_buffer_pointer()
        except Exception:
            # sharded arrays have no single buffer pointer; two leaves
            # alias iff their per-device shards do, so the first
            # addressable shard's pointer is a sufficient identity
            try:
                ptr = leaf.addressable_shards[0].data.unsafe_buffer_pointer()
            except Exception:
                ptr = None
        if ptr is not None:
            if ptr in seen:
                leaf = jnp.copy(leaf)
            else:
                seen.add(ptr)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def _snapshot_small_carries(state):
    """Pre-donation device copies of the NON-buffer EngineState leaves —
    regime carry, dedupe carries, indicator carry; all (S,)/(S, k)-scale.
    The donated dispatch's overflow fallback re-evaluates from these plus
    the post-tick buffers (updates only feed the buffer scatter, which by
    then has already happened), so no code path ever reads a donated
    buffer. One jitted dispatch; ~60 small output buffers, O(100 KB)."""
    global _copy_small_carries
    if _copy_small_carries is None:
        import jax
        import jax.numpy as jnp

        _copy_small_carries = jax.jit(
            lambda t: jax.tree_util.tree_map(jnp.copy, t)
        )
    return _copy_small_carries(
        (
            state.regime_carry,
            state.mrf_last_emitted,
            state.pt_last_signal_close,
            state.indicator_carry,
        )
    )


class _PendingTick(NamedTuple):
    """A dispatched-but-not-yet-emitted tick riding the device pipeline."""

    wire: Any  # (L,) device array — async D2H already started
    fallback: Any  # () -> TickOutputs — re-runs the FULL step (pure; used
    # only on wire overflow or a payload-less wire)
    ts_ms: int
    ts5: int
    ts15: int
    bucket15: int
    dispatched_at: float  # perf_counter at dispatch (signal-lag metric)
    rows: Any  # FrozenRows — row→symbol AS OF dispatch (registry churn
    # between dispatch and finalize must not re-attribute fired rows)
    trace: Any  # TickTrace (or NULL_TRACE when sampled out) — opened at
    # dispatch, closed when this tick finalizes; its trace_id is the
    # provenance every sink payload carries
    # double-buffered donation only: this tick's post state, recycled as
    # the NEXT dispatch's donated scratch slot once the tick finalizes
    # (and its fallback can no longer need the buffers). None elsewhere.
    spare: Any = None
    # which drive dispatched this tick ("serial" / "scanned" / "backtest")
    # — finalize attributes its decode/emit host-phase dwell to it
    drive: str = "serial"
    # perf_counter of the OLDEST pending candle this tick drained (None
    # when unknown) — the ingest→dispatch freshness anchor
    ingest_mono: Any = None
    # batch-decoded (WireFired, ctx) from unpack_wire_block when the chunk
    # drive already paid the decode in one vectorized pass (ISSUE 17);
    # None → finalize decodes this wire itself
    unpacked: Any = None


def _pow2_bucket(m: int, floor: int = 4) -> int:
    """Power-of-two size bucket (min ``floor``) shared by every scanned
    array dimension — slot rows, fold depth chunks, scan length. One copy:
    the bucketing policy directly controls the jit executable count."""
    size = floor
    while size < m:
        size *= 2
    return size


def _knob(config, name: str, default):
    """Config attribute with a default for stub configs that lack it.
    Unlike ``getattr(...) or default``, a present-but-zero value passes
    through — 0 is a documented setting for several delivery knobs
    (e.g. BQT_WAL_COMPACT_EVERY=0 disables auto-compaction)."""
    val = getattr(config, name, None)
    return default if val is None else val


def _scan_fallback_unavailable():
    """Fallback slot of a scanned tick's fabricated _PendingTick. Never
    reachable: the chunked drive re-drives overflowed chunks serially
    BEFORE any decode, and every scanned wire carries the full payload."""
    raise RuntimeError(
        "scanned tick has no per-tick fallback — overflow chunks must be "
        "re-driven serially (SignalEngine._flush_scan_plan)"
    )


class _ScanTickPlan(NamedTuple):
    """One replayed tick staged for a fused scan-chunk dispatch: its
    drained update sub-batches plus every host-resolved input the serial
    dispatch would have built, captured at PLAN time with the serial
    drive's exact ordering (breadth momentum BEFORE this tick's bucket
    refresh, adp scalars after — see ``SignalEngine.process_ticks_scanned``)."""

    now_ms: int
    ts5: int
    ts15: int
    bucket15: int
    batches5: list
    batches15: list
    momentum_ok: bool  # grid-policy breadth verdict as of finalize(t-1)
    breadth: Any  # MarketBreadthSeries as of this tick's dispatch
    tracked: Any  # (S,) bool registry occupancy AS OF this tick — a
    # later chunk-breaking tick's registry add must not leak backwards
    # (the context coverage gate counts tracked rows)
    oi: Any  # (S,) np f32 or None (cache empty)
    adp: tuple  # breadth_scalars() at dispatch
    quiet: bool
    btc_row: int
    rows: Any  # FrozenRows at plan time (no churn inside a chunk)
    is_futures: bool
    dominance_is_losers: bool
    market_domination_reversal: bool
    # ingest-arrival perf_counter of this tick's oldest drained candle
    # (freshness stamp; None when the batchers were already empty)
    ingest_mono: Any = None


class SignalEngine:
    """Owns the device state and drives ticks from queued klines."""

    def __init__(
        self,
        config: Config,
        binbot_api: BinbotApi,
        telegram_consumer: TelegramConsumer,
        at_consumer: AutotradeConsumer,
        registry: SymbolRegistry | None = None,
        window: int = 400,
        futures_api: Any | None = None,
        context_config: ContextConfig = ContextConfig(),
        btc_symbol: str = "BTCUSDT",
        enabled_strategies: set[str] | None = None,
        pipeline_depth: int = 0,
    ) -> None:
        self.config = config
        self.binbot_api = binbot_api
        self.telegram_consumer = telegram_consumer
        self.at_consumer = at_consumer
        self.capacity = config.max_symbols
        self.window = int(window)
        self.registry = registry or SymbolRegistry(self.capacity)
        self.batcher5 = IngestBatcher(self.registry)
        self.batcher15 = IngestBatcher(self.registry)
        self.state = initial_engine_state(self.capacity, window=window)
        # Production multi-chip mode (BQT_MESH_DEVICES>1): shard the
        # carried state over a 1-D `symbols` mesh ONCE; jit sharding
        # propagation keeps every tick's outputs (incl. the carried state)
        # sharded, so the per-tick path never re-places anything. Host
        # ingest and emission are unchanged — the wire is tiny and
        # fully replicated by its final concatenate reduction.
        self.mesh = None
        mesh_n = getattr(config, "mesh_devices", 0)
        if mesh_n and mesh_n > 1:
            import jax

            from binquant_tpu.parallel.mesh import make_mesh, shard_engine_state

            devices = jax.devices()
            if len(devices) < mesh_n:
                logging.warning(
                    "BQT_MESH_DEVICES=%d but only %d device(s) visible; "
                    "running single-chip",
                    mesh_n,
                    len(devices),
                )
            elif self.capacity % mesh_n != 0:
                logging.warning(
                    "capacity %d not divisible by mesh size %d; "
                    "running single-chip",
                    self.capacity,
                    mesh_n,
                )
            else:
                self.mesh = make_mesh(devices[:mesh_n])
                self.state = shard_engine_state(self.state, self.mesh)
                logging.info(
                    "symbol axis sharded over %d devices (%s)",
                    mesh_n,
                    self.mesh.shape,
                )
        self.context_config = context_config
        self.btc_symbol = btc_symbol
        self.notifier = MarketRegimeNotifier(env=config.env)
        self.leverage_calibrator = LeverageCalibrator(
            binbot_api, at_consumer.exchange
        )
        self.oi_cache = OpenInterestCache(futures_api)
        self.market_breadth: MarketBreadthSeries | None = None
        self.grid_only_policy = GridOnlyPolicy.disabled("not_evaluated")
        self._last_breadth_bucket = -1
        self._last_calibration_bucket = -1
        self._calibration_task: asyncio.Task | None = None
        self._pending_oi: dict[int, float] = {}
        # last valid regime/strength seen (checkpoint introspection only —
        # the quiet-hours override reads the CURRENT tick's context
        # device-side, engine/step.py)
        self._last_regime: int | None = None
        self._last_transition_strength: float = 0.0
        # per-bar emission dedupe: (strategy, symbol) -> last emitted bar
        # open ts. consume_loop re-ticks every second within a bucket; a
        # standing trigger must fire at most once per bar (the reference
        # dispatches once per candle arrival).
        self._last_emitted: dict[tuple[str, str], int] = {}
        self.enabled_strategies: frozenset[str] | None = (
            None if enabled_strategies is None else frozenset(enabled_strategies)
        )
        self.heartbeat_path = Path(config.heartbeat_path)
        self.ticks_processed = 0
        self.signals_emitted = 0
        # liveness bookkeeping surfaced by /healthz (obs.exposition):
        # last successful heartbeat write, last processed tick, and the
        # write-failure counters touch_heartbeat maintains
        self._last_heartbeat_s: float | None = None
        self._last_tick_wall_s: float | None = None
        self.heartbeat_write_failures = 0
        self._hb_consecutive_failures = 0
        self._hb_last_warn = float("-inf")
        # ticks whose fired set overflowed the wire's compaction slots
        # (exact count — the latency reservoir is capped and also times
        # payload-less fallbacks)
        self.overflow_ticks = 0
        # optional CheckpointManager; consume_loop snapshots through it
        self.checkpoint = None
        # injectable ws reconnect-health tracker for health_snapshot's
        # ``ws`` section (None = the io.websocket module singleton the
        # live connectors feed); tests script their own WsHealth here
        self.ws_health = None
        # per-stage latency histograms (SURVEY §5: the p99<50ms budget is
        # measured in production, not guessed)
        self.latency = LatencyTracker()
        # per-tick span traces + slow-tick flight recorder (obs/tracing.py);
        # histograms prove the p99 budget is breached, the trace says WHERE
        self.tracer = Tracer(
            sample=float(getattr(config, "trace_sample", 1.0)),
            slow_ms=float(getattr(config, "trace_slow_ms", 50.0)),
            ring=int(getattr(config, "trace_ring", 256)),
        )
        # unified SLO registry + verdict plane (ISSUE 16): every plane's
        # SLO (freshness, staleness, per-sink delivery) judged behind one
        # burn/recover event model, plus the delivery/fan-out invariant
        # probes — folded into one machine-readable verdict at
        # GET /debug/slo. Observation-driven: the owning monitors feed it
        # from their existing paths; no per-tick dispatch of its own.
        self.slo = None
        if bool(getattr(config, "slo_enabled", False)):
            from binquant_tpu.obs.slo import SloRegistry

            self.slo = SloRegistry(
                event_every=int(_knob(config, "slo_event_every", 256)),
            )
        # latency observatory (ISSUE 11): candle-close→sink-ack freshness
        # stamps + the shared host-phase dwell taxonomy (obs/latency.py).
        # Host-only instruments — the device wire is untouched either way.
        self.freshness = FreshnessTracker(
            enabled=bool(getattr(config, "freshness_enabled", True)),
            slo_ms=float(getattr(config, "freshness_slo_ms", 0.0) or 0.0),
            slo=self.slo,
        )
        if (
            self.slo is not None
            and self.freshness.enabled
            and self.freshness.slo_ms > 0
        ):
            # the PR 11 freshness SLO, re-homed into the unified registry
            self.slo.register("freshness", "freshness", self.freshness.slo_ms)
        self.host_phase = PhaseAccountant(
            enabled=bool(getattr(config, "host_phase_enabled", True))
        )
        # signal-outcome observatory (ISSUE 12): every emitted signal
        # registers here and matures device-side at fixed 5m-bar horizons
        # (obs/outcomes.py). Host-side registry + one small jit'd gather
        # per maturation tick — the device wire is untouched either way.
        self.outcomes = OutcomeTracker(
            enabled=bool(getattr(config, "outcomes_enabled", True)),
            horizons=tuple(
                getattr(config, "outcome_horizons", None) or (1, 4, 16, 96)
            ),
            cap=int(getattr(config, "outcome_cap", 1024) or 1024),
        )
        # subscription fan-out plane (ISSUE 14): compile user
        # subscriptions into device bitset planes and join every fired
        # tick's deduped signal set against them in ONE extra dispatch;
        # matched frames ride the outbox + the WS/SSE hub. BQT_FANOUT=0
        # (the tier-1 lane's default) keeps the three-sink path
        # byte-identical — no plane, no kernel, no outbox.
        self.fanout = None
        if bool(getattr(config, "fanout_enabled", False)):
            from binquant_tpu.fanout.plane import FanoutPlane

            # outbox partition count: explicit knob, else the symbol
            # mesh size (per-shard delivery partitions merged under one
            # global cursor; 1 = the classic single-file outbox)
            _ob_shards = int(
                _knob(config, "fanout_outbox_shards", 0) or 0
            ) or (self.mesh.devices.size if self.mesh is not None else 1)
            self.fanout = FanoutPlane(
                self.registry,
                capacity=int(_knob(config, "fanout_capacity", 1024)),
                outbox_path=(
                    getattr(config, "fanout_outbox_path", "") or None
                ),
                outbox_cap=int(_knob(config, "fanout_outbox_cap", 4096)),
                conn_queue_max=int(_knob(config, "fanout_conn_queue", 256)),
                outbox_shards=_ob_shards,
                snapshot_path=(
                    getattr(config, "fanout_snapshot_path", "") or None
                ),
                snapshot_shards=int(
                    _knob(config, "fanout_snapshot_shards", 0) or 0
                ),
                compact_frac=float(
                    _knob(config, "fanout_compact_frac", 0.0) or 0.0
                ),
                resume_tail=int(
                    _knob(config, "fanout_resume_tail", 0) or 0
                ),
            )
            # snapshot-warm boot (ISSUE 20): restore the compiled planes
            # by load instead of replaying the whole subscription
            # population — ~20 s → sub-second at the 1M-user scale; a
            # missing/torn/mismatched archive silently starts cold
            if self.fanout.snapshot_path is not None:
                self.fanout.try_restore_snapshot()
            if self.slo is not None:
                # PR 14 recipient-set integrity as a verdict invariant
                self.slo.add_invariant(
                    "fanout_recipient_set",
                    self.fanout.recipient_set_invariant,
                )
        # durable signal delivery plane (ISSUE 13): finalize enqueues and
        # returns; per-sink workers own retries/backoff/breakers, and the
        # autotrade class is WAL-durable at-least-once across a process
        # kill. BQT_DELIVERY=0 (the tier-1 lane's default) keeps the
        # pre-plane inline sink dispatch byte-identical.
        self.delivery = None
        self.delivery_health = None
        if bool(getattr(config, "delivery_enabled", False)):
            from binquant_tpu.io.delivery import DeliveryPlane
            from binquant_tpu.io.emission import make_signal_sinks

            sinks = make_signal_sinks(
                binbot_api, telegram_consumer, at_consumer
            )
            if self.fanout is not None:
                # the broadcast tier as a fourth, lossy consumer group
                # (ROADMAP item 2's horizontal-scaling seam): the hub
                # handoff runs on a delivery worker, not the tick thread
                from binquant_tpu.fanout.plane import FanoutSink

                sinks.append(FanoutSink(self.fanout))
            # delivery-plane health collector (ISSUE 16): per-sink
            # close→final-ack lag + the lazily-minted delivery.<sink>
            # SLOs; disabled instances are allocation-free on the ack path
            from binquant_tpu.obs.delivery_health import DeliveryHealth

            self.delivery_health = DeliveryHealth(
                enabled=bool(
                    getattr(config, "delivery_health_enabled", False)
                ),
                window=int(_knob(config, "slo_window", 512)),
                slo=self.slo,
                slo_ms=float(_knob(config, "delivery_slo_ms", 0.0)),
            )
            self.delivery = DeliveryPlane(
                sinks=sinks,
                wal_path=getattr(config, "delivery_wal_path", "") or None,
                queue_max=int(_knob(config, "delivery_queue_max", 512)),
                attempt_timeout_s=float(
                    _knob(config, "delivery_attempt_timeout_s", 5.0)
                ),
                retry_max=int(_knob(config, "delivery_retry_max", 3)),
                backoff_s=float(_knob(config, "delivery_backoff_s", 0.25)),
                backoff_max_s=float(
                    _knob(config, "delivery_backoff_max_s", 30.0)
                ),
                breaker_threshold=int(
                    _knob(config, "delivery_breaker_threshold", 5)
                ),
                breaker_cooldown_s=float(
                    _knob(config, "delivery_breaker_cooldown_s", 30.0)
                ),
                wal_compact_every=int(_knob(config, "wal_compact_every", 256)),
                freshness=self.freshness,
                health=self.delivery_health,
            )
            if self.slo is not None:
                # PR 13 zero-loss/zero-duplicate contracts + breaker
                # state as verdict invariants (no false green while a
                # sink is down)
                self.slo.add_invariant(
                    "delivery_zero_loss", self.delivery.zero_loss_invariant
                )
                self.slo.add_invariant(
                    "delivery_zero_duplicate",
                    self.delivery.zero_duplicate_invariant,
                )
                self.slo.add_invariant(
                    "delivery_breakers_closed",
                    self.delivery.breakers_closed_invariant,
                )
        # tick_seq source for traces: advances on every dispatch ATTEMPT
        # (ticks_processed only counts successes — deriving the seq from
        # it would hand a failed tick's number to the retry, and tick_seq
        # is the human-facing join key trace_report filters on)
        self._tick_seq = 0
        # Fired-tick fast path: consume_loop lands + emits a dispatched
        # tick's wire as soon as it arrives instead of waiting for the next
        # tick to evict it — cuts the depth-1 emission lag from one full
        # cadence (~1 s) to roughly the device round trip. Off for replay
        # determinism when BQT_EARLY_EMIT=0.
        self.early_emit = getattr(config, "early_emit", True)
        # Tick pipelining: dispatch tick i to the device, start its wire's
        # async D2H, and emit tick i-1's already-landed wire — the host
        # never blocks on the device round trip. depth=0 is the serial
        # fallback (dispatch + fetch + emit of the SAME tick; deterministic
        # tick→signal attribution for replay/A-B). depth=1 is the live
        # default (main.py): at a 1 s cadence the wire has the whole idle
        # gap to land, so the fetch is free. Deeper pipelines only matter
        # when ticks run back-to-back against a high-RTT (tunneled) device.
        self.pipeline_depth = int(pipeline_depth)
        self._pending: deque[_PendingTick] = deque()
        # HostInputs template built once: re-creating all 16 device arrays
        # per tick costs a dozen extra H2D dispatches
        self._base_inputs = None
        # (wire key, update shapes) whose full-step fallback compile has
        # been background-warmed (see _dispatch_tick)
        self._fallback_warmed: set[tuple] = set()
        # per-name device-scalar cache: breadth scalars change once per
        # bucket and the flags rarely — re-uploading identical values
        # every tick is allocation churn that shows up as inputs_build
        # p99 spikes (GC) on the 50 ms budget
        self._scalar_cache: dict[str, tuple[Any, Any]] = {}
        self._tracked_cache: tuple[int, Any] | None = None
        # Per-tick tracked-mask snapshot set by _redrive_serial: a scan
        # plan broken by registry churn re-drives its buffered ticks AFTER
        # the churn already mutated the registry, so each re-driven tick
        # must dispatch with the mask captured when it was planned, not
        # the live one (digest `tracked` parity with a never-scanned run).
        self._tracked_override: Any = None
        self._nan_oi_cache: Any = None
        # -- incremental indicator fast path (engine/step.py incremental=True)
        # The host decides per tick: carried state is only valid when every
        # update since the last full recompute was a clean strictly-newer
        # single-bar append. Cold start, mid-history rewrites, backfill
        # folds, registry churn, and the periodic drift audit all route the
        # tick to the full step (counted in bqt_full_recompute_total),
        # which re-anchors the carry from the windows.
        self.incremental = bool(getattr(config, "incremental_enabled", True))
        if self.incremental and self.window < MIN_INCR_ENGINE_WINDOW:
            # fail fast: a too-short ring would pass dispatch and wedge the
            # consume loop on the FIRST full-recompute tick instead (the
            # ABP carry init needs score_lookback+1 trailing bars)
            raise ValueError(
                f"window {self.window} < {MIN_INCR_ENGINE_WINDOW}, the "
                "incremental engine's minimum ring size (engine/step.py "
                "MIN_INCR_ENGINE_WINDOW) — grow the window or set "
                "BQT_INCREMENTAL=0"
            )
        self.carry_audit_every = int(
            getattr(config, "carry_audit_every_ticks", 256) or 0
        )
        # why the carry is desynced (None = synced); seeded as cold start
        self._carry_desync_reason: str | None = "cold_start"
        # last applied open-time per registry row, per interval — the
        # host-side mirror that detects rewrites/out-of-order deliveries
        # without a device fetch
        self._host_latest: dict[str, np.ndarray] = {
            "5m": np.full(self.capacity, -1, dtype=np.int64),
            "15m": np.full(self.capacity, -1, dtype=np.int64),
        }
        # exact counters surfaced by health_snapshot / tests
        self.incremental_ticks = 0
        self.full_recompute_ticks = 0
        # per-reason tally mirroring bqt_full_recompute_total{reason} at
        # engine scope — the scenario lane asserts a drill's scripted
        # routing (rewrite storms -> "rewrite", listing waves -> "churn")
        # without reading the process-global registry
        self.full_recompute_reasons: dict[str, int] = {}
        # -- donated live buffers (engine/step.py tick_step_wire_donated)
        # BQT_DONATE: the wire step updates the ring buffers IN PLACE
        # (erases the functional scatter's allocate+copy — ~0.23 GB/tick of
        # residual bytes at 2048×400). Engaged per dispatch only when safe
        # (_use_donated_step); counters below are test/health introspection.
        self._donate_cfg = bool(getattr(config, "donate_enabled", True))
        self.donated_ticks = 0
        self.donated_state_resets = 0
        # double-buffered donation (ISSUE 9, pipeline_depth >= 2): free
        # resident state slots. A finalized tick's post state parks here
        # and a later dispatch donates it as the scratch the outputs are
        # written into, so the in-flight ticks' own post states stay live
        # for their fallbacks. Empty = no free slot (a fresh zeros state
        # is allocated at the next double-buffered dispatch); a small
        # free LIST (not one slot) so deeper pipelines' flush drains
        # don't drop slots and re-allocate. The generation counter bumps
        # on every cold reset so a pending tick from a FAILED lineage
        # cannot rotate its (possibly poisoned) pre-reset state back
        # into the pool at finalize.
        self._spare_slots: list = []
        self._spare_slots_max = 4
        self._state_generation = 0
        # a finalized tick whose post state is STILL self.state (light
        # load: every tick finalizes before the next dispatch) cannot
        # rotate immediately — it parks here and is promoted into the
        # free pool by the NEXT such finalize, whose wire fetch proves
        # the computation that read the parked buffers has completed.
        # Without this, light-load depth>=2 would allocate + zero-fill a
        # fresh ~2x(S,W,F) scratch on every dispatch.
        self._deferred_spare = None
        # -- scanned replay chunks (engine/step.py tick_step_scan, ISSUE 5)
        # Multi-tick lanes (replay, catch-up, backtesting) fuse runs of
        # clean-append incremental ticks into one lax.scan dispatch of up
        # to BQT_SCAN_CHUNK ticks; counters are test/health introspection.
        self.scan_chunk = max(int(getattr(config, "scan_chunk", 64) or 64), 2)
        self.scanned_ticks = 0
        self.scan_chunks = 0
        self.scan_overflow_reruns = 0
        # -- time-batched backtest backend (binquant_tpu/backtest, ISSUE 6)
        # Full-recompute chunks over (S, W+T) extended buffers; requires
        # BQT_INCREMENTAL=0 engines. Chunk bounds the (T, S, W, F) gathered
        # window views' memory — the knob to drop on small boxes.
        # floor at _SCAN_MIN_TICKS: a smaller chunk would make every plan
        # fail the too-short check and silently degrade to the fully
        # serial path (backtest_chunks=0 with no error pointing at it)
        self.backtest_chunk = max(
            int(getattr(config, "backtest_chunk", 16) or 16),
            self._SCAN_MIN_TICKS,
        )
        self.backtest_ticks = 0
        self.backtest_chunks = 0
        self.backtest_overflow_reruns = 0
        # Extension-invariant chunk precompute (ISSUE 17, BQT_EXT_INVARIANT):
        # feature packs / symbol features / BTC beta-corr run once over the
        # (S, W+T) extension instead of per-tick over gathered views.
        # Governed by the gate-margin tolerance contract — the default (off)
        # keeps the chunk drives bit-identical to the serial step.
        self.ext_invariant = bool(getattr(config, "ext_invariant", False))
        # Explicit StrategyParams override (None = the kernels' baked
        # defaults, the live graph). Set by the backtest driver when a run
        # carries non-default params so the SERIAL re-entries (cold start,
        # rewrites, overflow re-drives) evaluate with the SAME thresholds
        # as the batched chunks — a custom-params run must never silently
        # mix two parameter sets.
        self.strategy_params = None
        # -- numeric-health observatory (ISSUE 7)
        # Device-side digest riding the wire (BQT_NUMERIC_DIGEST; a STATIC
        # flag — off compiles the pre-digest wire bit-identically), decoded
        # every finalize into bqt_numeric_* metrics + /healthz; leakage
        # past BQT_NUMERIC_NAN_BUDGET force-emits numeric_anomaly events.
        self.numeric_digest = bool(getattr(config, "numeric_digest", True))
        self.numeric = NumericHealthMonitor(
            nan_budget=int(getattr(config, "numeric_nan_budget", 0) or 0),
            event_every=self.carry_audit_every or 256,
        )
        # -- ingest-health observatory (ISSUE 15)
        # Device-side ingest digest riding the wire after the numeric block
        # (BQT_INGEST_DIGEST; a STATIC flag — off compiles the pre-ingest
        # wire bit-identically) + the host-side per-symbol watermark/
        # counter monitor feeding bqt_ingest_* and GET /debug/symbols.
        # Staleness past BQT_INGEST_STALE_BUDGET force-emits
        # ingest_anomaly events and degrades the /healthz status.
        self.ingest_digest = bool(getattr(config, "ingest_digest", True))
        self.ingest_monitor = IngestHealthMonitor(
            self.registry,
            enabled=self.ingest_digest,
            stale_budget=int(getattr(config, "ingest_stale_budget", 0) or 0),
            event_every=self.carry_audit_every or 256,
            slo=self.slo,
        )
        if self.slo is not None and self.ingest_monitor.enabled:
            # the PR 15 staleness SLO, re-homed into the unified registry
            self.slo.register(
                "staleness",
                "staleness",
                float(self.ingest_monitor.stale_budget),
                unit="rows",
            )
        # device-side (8,) accumulator of the current tick's fold-slot
        # ingest counts (counted fold steps) — consumed (and reset) by the
        # next evaluated dispatch; a cached zeros array keeps the dispatch
        # signature stable on fold-free ticks
        self._ingest_fold_counts = None
        self._ingest_zero_counts = None
        # Carry-drift audit meters (BQT_DRIFT_METER): every audit tick
        # measures per-family carried-vs-fresh drift BEFORE the resync
        # overwrites the carry — the audit becomes a measured correctness
        # signal instead of a blind reset. Incremental engines only (a
        # classic engine has no carry to drift).
        self.drift_meter_enabled = (
            bool(getattr(config, "drift_meter", True)) and self.incremental
        )
        self.drift = DriftMeter(tol=float(getattr(config, "drift_tol", 0.05)))
        # update-bucket shapes whose drift-measurement compile has been
        # background-warmed (see _dispatch_tick_inner — the meter must not
        # stall the audit tick it instruments with its own first compile)
        self._drift_warmed: set[tuple] = set()

    # -- ingest -------------------------------------------------------------

    def ingest(self, kline: dict) -> None:
        """Route one closed candle to its interval batcher by bar duration.

        Only 5m and 15m frames are accepted; anything else (a stray 1m/1h
        subscription) is rejected rather than corrupting buf15.
        """
        duration_s = (int(kline["close_time"]) - int(kline["open_time"])) // 1000
        if abs(duration_s - FIVE_MIN_S) <= 1:
            self.batcher5.add(kline)
        elif abs(duration_s - FIFTEEN_MIN_S) <= 1:
            self.batcher15.add(kline)
        else:
            logging.warning(
                "dropping kline with unsupported duration %ss for %s",
                duration_s,
                kline.get("symbol"),
            )
            return
        if self.ingest_monitor.enabled:
            # arrival watermark + per-exchange feed lag (the ws parsers
            # stamp "exchange"; replay/fixture streams default binance)
            self.ingest_monitor.note_arrival(
                str(kline.get("symbol", "")).strip().upper(),
                int(kline["close_time"]),
                exchange=str(kline.get("exchange", "binance")),
            )

    # -- startup history backfill ---------------------------------------------

    @staticmethod
    def _empty_updates():
        return pad_updates(
            np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros((0, 10), np.float32), size=4,
        )

    def _fold_updates(
        self,
        batches5: list,
        batches15: list,
        advance_carry: bool = False,
        btc_row: int = -1,
    ):
        """Apply all but the FINAL sub-batch pair with the cheap
        update-only step (ordered sub-batch replay — evaluating each would
        advance dedupe carries and discard earlier signals); returns the
        final (upd5, upd15) pair for the caller to apply or evaluate.

        ``advance_carry=True`` folds with the carry-advancing step so a
        multi-bar drain of clean appends (e.g. three 5m bars per 15m tick)
        keeps the incremental indicator state in sync — only valid when
        the caller verified every sub-batch is a strictly-newer append.
        ``btc_row`` keeps the beta/corr positional pairing advancing
        through the folds (engine/step.py advance_indicator_carry)."""
        count = self.ingest_digest
        if advance_carry:
            fold = lambda st, a, b: apply_updates_carry_step(
                st, a, b, btc_row=btc_row
            )
        else:
            fold = apply_updates_step
        empty = self._empty_updates()
        upd5 = [pad_updates(*b) for b in batches5] or [empty]
        upd15 = [pad_updates(*b) for b in batches15] or [empty]
        n = max(len(upd5), len(upd15))
        if not advance_carry and n - 1 >= self._SCAN_FOLD_MIN:
            # deep update-only folds (backfill chunks, post-restore gap
            # catch-up) collapse into ~⌈(n-1)/chunk⌉ scanned dispatches
            # instead of n-1 — an N-bar gap at restart costs ~N/T launches
            self._scan_fold_prefix(batches5, batches15, n)
        else:
            for i in range(n - 1):
                a = upd5[i] if i < len(upd5) else empty
                b = upd15[i] if i < len(upd15) else empty
                if count:
                    # counted twins: classify each fold slot against the
                    # pre-fold ring inside the SAME dispatch, so the next
                    # evaluated tick's ingest digest covers the whole
                    # drain (engine/step.py counted fold steps)
                    if advance_carry:
                        self.state, self._ingest_fold_counts = (
                            apply_updates_carry_step_counted(
                                self.state, a, b, btc_row=btc_row,
                                counts=self._ingest_fold_acc(),
                            )
                        )
                    else:
                        self.state, self._ingest_fold_counts = (
                            apply_updates_step_counted(
                                self.state, a, b, self._ingest_fold_acc()
                            )
                        )
                else:
                    self.state = fold(self.state, a, b)
        return (
            upd5[n - 1] if n - 1 < len(upd5) else empty,
            upd15[n - 1] if n - 1 < len(upd15) else empty,
        )

    def _ingest_fold_acc(self):
        """The running (8,) fold-count accumulator (device array; a cached
        zeros template when no fold has counted yet this tick)."""
        if self._ingest_fold_counts is not None:
            return self._ingest_fold_counts
        if self._ingest_zero_counts is None:
            import jax.numpy as jnp

            self._ingest_zero_counts = jnp.zeros((8,), dtype=jnp.float32)
        return self._ingest_zero_counts

    def _take_ingest_fold_counts(self):
        """Consume the accumulated fold counts for the tick being
        dispatched (None while the digest is off — the traced step ignores
        the argument entirely, keeping the pre-ingest graph)."""
        if not self.ingest_digest:
            return None
        counts = self._ingest_fold_counts
        self._ingest_fold_counts = None
        return counts if counts is not None else self._ingest_fold_acc()

    def _begin_plan_ingest_state(self):
        """Plan-start hook for the chunked drives: snapshot the monitor
        (the rewind anchor) and DISCARD any pending fold-count
        accumulator. Counts from update-only drains (backfill, restore
        catch-up) ride the next SERIAL evaluated tick's digest; a chunk
        that batches the immediately-following tick computes its own
        counts from its own update views, so a stale accumulator would
        otherwise leak into whichever unrelated serial tick dispatches
        after the chunk. The host monitor counted those bars either way —
        the digest is per-tick telemetry, not the ledger."""
        self._ingest_fold_counts = None
        return self.ingest_monitor.snapshot_state()

    # update-only folds shorter than this keep the per-sub-batch dispatch
    # loop (a fresh scan compile isn't worth a handful of launches)
    _SCAN_FOLD_MIN = 8

    def _scan_fold_prefix(self, batches5: list, batches15: list, n: int) -> None:
        """Fold sub-batch slot pairs [0, n-2] through ``apply_updates_scan``
        in ``scan_chunk``-bounded dispatches. Slot lengths are padded to one
        power-of-two row bucket per interval and the scan length to a
        power-of-two bucket (both bound the executable count); padding
        slots are all-(-1) rows, which ``apply_updates`` drops — exact
        no-ops, so no validity mask is needed."""
        from binquant_tpu.engine.buffer import NUM_FIELDS

        bucket = _pow2_bucket
        prefix5 = batches5[: n - 1]
        prefix15 = batches15[: n - 1]
        u5_rows = bucket(max((len(b[0]) for b in prefix5), default=1))
        u15_rows = bucket(max((len(b[0]) for b in prefix15), default=1))
        total = n - 1
        chunk = max(self.scan_chunk, self._SCAN_FOLD_MIN)
        for start in range(0, total, chunk):
            stop = min(start + chunk, total)
            tb = bucket(stop - start)
            r5 = np.full((tb, u5_rows), -1, np.int32)
            t5 = np.full((tb, u5_rows), -1, np.int32)
            v5 = np.zeros((tb, u5_rows, NUM_FIELDS), np.float32)
            r15 = np.full((tb, u15_rows), -1, np.int32)
            t15 = np.full((tb, u15_rows), -1, np.int32)
            v15 = np.zeros((tb, u15_rows, NUM_FIELDS), np.float32)
            for i in range(start, stop):
                if i < len(prefix5):
                    rows, ts, vals = pad_updates(*prefix5[i], size=u5_rows)
                    r5[i - start], t5[i - start], v5[i - start] = rows, ts, vals
                if i < len(prefix15):
                    rows, ts, vals = pad_updates(*prefix15[i], size=u15_rows)
                    r15[i - start], t15[i - start], v15[i - start] = (
                        rows, ts, vals,
                    )
            if self.ingest_digest:
                self.state, self._ingest_fold_counts = (
                    apply_updates_scan_counted(
                        self.state, (r5, t5, v5), (r15, t15, v15),
                        self._ingest_fold_acc(),
                    )
                )
            else:
                self.state = apply_updates_scan(
                    self.state, (r5, t5, v5), (r15, t15, v15)
                )

    def _note_applied(
        self, batches5: list, batches15: list, commit: bool = True
    ) -> bool:
        """Update the host-side per-row latest-open-time mirror with the
        sub-batches about to be applied; returns True when EVERY update is
        a clean strictly-newer append (carry-advance safe). Must be called
        exactly once per drained batch set, in apply order.

        ``commit=False`` computes the verdict on a scratch copy without
        mutating the mirror — the scanned drive peeks before deciding
        whether a tick joins a chunk (committed then) or re-enters the
        serial path (which judges and commits itself)."""
        clean = True
        feed_monitor = commit and self.ingest_monitor.enabled
        for key, batches in (("5m", batches5), ("15m", batches15)):
            latest = self._host_latest[key]
            if not commit:
                latest = latest.copy()
            for rows, ts, _ in batches:
                if len(rows) == 0:
                    continue
                rows = np.asarray(rows, dtype=np.int64)
                ts64 = np.asarray(ts, dtype=np.int64)
                ok = (rows >= 0) & (rows < self.capacity)
                rows, ts64 = rows[ok], ts64[ok]
                if np.any(ts64 <= latest[rows]):
                    clean = False
                if feed_monitor:
                    # per-symbol watermarks/counters, classified against
                    # the pre-apply mirror (the same routing the device
                    # resolves); peeks (commit=False) never feed
                    self.ingest_monitor.note_applied_batch(
                        key, rows, ts64, latest[rows]
                    )
                np.maximum.at(latest, rows, ts64)
        return clean

    def _flush_batchers(self) -> None:
        """Drain both batchers into the device buffers (update-only).

        Used by backfill: the carry is NOT advanced here (hundreds of bars
        fold in), so the next evaluated tick runs the full recompute,
        which re-anchors it from the final windows."""
        batches5, batches15 = self.batcher5.drain(), self.batcher15.drain()
        if batches5 or batches15:
            self._note_applied(batches5, batches15)
            self._mark_carry_desynced("backfill")
        u5, u15 = self._fold_updates(batches5, batches15)
        if self.ingest_digest:
            # the final slot is update-only here too (no evaluation):
            # count it into the accumulator the next evaluated tick drains
            self.state, self._ingest_fold_counts = apply_updates_step_counted(
                self.state, u5, u15, self._ingest_fold_acc()
            )
        else:
            self.state = apply_updates_step(self.state, u5, u15)

    def _mark_carry_desynced(self, reason: str) -> None:
        """Record that the carried indicator state no longer matches the
        windows; the next tick dispatches the full recompute (which
        resyncs). First reason wins until a full tick clears it."""
        if reason == "churn":
            # every drive marks churn at its drain (serial, scanned and
            # backtest planners alike) — one hook covers all three
            self.ingest_monitor.note_churn()
        if self._carry_desync_reason is None:
            self._carry_desync_reason = reason

    def backfill(
        self,
        symbols: list[str],
        fetch,
        now_ms: int | None = None,
        chunk: int = 50,
        concurrency: int = 8,
    ) -> int:
        """Seed both interval buffers via REST history before going live.

        The reference seeds 400 bars/symbol at boot and per message
        (klines_provider.py:196-222,278-293); without this the engine is
        strategy-blind for ~MIN_BARS*15m (~25 h) after a cold start.
        ``fetch(symbol, '5m'|'15m')`` returns normalized kline dicts (see
        ``io.exchanges.make_history_fetcher``). Only bars closed before
        ``now_ms`` are loaded. Per-symbol failures are logged and skipped;
        buffers are flushed every ``chunk`` symbols to bound host memory.

        Fetches run ``concurrency``-way in a thread pool (round 2 was one
        serial round trip at a time — minutes of boot at 2000 symbols);
        batcher mutation stays on the calling thread. The Binance weight
        guard lives inside ``BinanceApi._on_response``: any worker that
        sees the account-global used-weight header past the soft cap
        sleeps, which throttles the whole pool under the 1200/min budget.
        """
        from concurrent.futures import ThreadPoolExecutor

        t_start = time.monotonic()
        now = int(now_ms if now_ms is not None else time.time() * 1000)
        ordered = [self.btc_symbol] + [
            s for s in symbols if s != self.btc_symbol
        ]
        loaded = 0
        requests = 0
        failures = 0

        def fetch_symbol(symbol: str):
            out = []
            for interval_key in ("5m", "15m"):
                try:
                    out.append((interval_key, fetch(symbol, interval_key)))
                except Exception:
                    logging.exception(
                        "backfill fetch failed for %s %s; skipping",
                        symbol,
                        interval_key,
                    )
                    out.append((interval_key, None))
            return out

        with ThreadPoolExecutor(max_workers=max(1, concurrency)) as pool:
            for i, results in enumerate(pool.map(fetch_symbol, ordered)):
                for interval_key, klines in results:
                    if klines is None:
                        failures += 1
                        continue
                    requests += 1
                    batcher = (
                        self.batcher5 if interval_key == "5m" else self.batcher15
                    )
                    for k in klines:
                        if int(k["close_time"]) <= now:
                            batcher.add(k)
                            loaded += 1
                if (i + 1) % chunk == 0:
                    self._flush_batchers()
        self._flush_batchers()
        logging.info(
            "backfill complete: %d bars across %d symbols in %.1fs "
            "(%d fetches ok, %d failed, %d-way)",
            loaded,
            len(ordered),
            time.monotonic() - t_start,
            requests,
            failures,
            concurrency,
        )
        return loaded

    # -- periodic jobs (15m bucket cadence) ----------------------------------

    async def _refresh_market_breadth(self, bucket: int) -> None:
        if bucket == self._last_breadth_bucket:
            return
        self._last_breadth_bucket = bucket
        try:
            self.market_breadth = await self.binbot_api.get_market_breadth()
        except Exception:
            logging.exception("market breadth refresh failed; keeping previous")

    def _run_leverage_calibration(self, bucket: int, context, rows=None) -> None:
        """Schedule the per-bucket leverage diff as a BACKGROUND worker.

        The calibrator walks every feature-valid row and PUTs changes —
        O(S) host work plus REST calls that must not ride the tick thread
        (VERDICT r4 item 4; the reference blocks its consumer here,
        ``consumers/klines_provider.py:305-319``). The tick only snapshots
        inputs: the wire-decoded calibration block and the dispatch-time
        ``FrozenRows`` (churn-safe). Single-flight: at the production
        900 s cadence runs never overlap; on accelerated clocks (bench,
        replay) a still-running worker skips the new bucket."""
        if bucket == self._last_calibration_bucket:
            return
        self._last_calibration_bucket = bucket
        task = self._calibration_task
        if task is not None and not task.done():
            logging.warning(
                "leverage calibration for bucket %s skipped: previous run "
                "still in flight (accelerated clock)",
                bucket,
            )
            return
        rows = rows if rows is not None else self.registry.frozen_rows()
        symbols = self.at_consumer.all_symbols

        async def _job() -> None:
            try:
                with self.latency.stage("leverage_calibration_worker"):
                    await asyncio.to_thread(
                        self.leverage_calibrator.calibrate_all,
                        context,
                        rows,
                        symbols,
                    )
            except Exception:
                logging.exception("leverage calibration crashed; continuing")

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no running loop (synchronous test harness): run inline, with
            # the same crash isolation the worker path has
            try:
                with self.latency.stage("leverage_calibration_worker"):
                    self.leverage_calibrator.calibrate_all(context, rows, symbols)
            except Exception:
                logging.exception("leverage calibration crashed; continuing")
            return
        # detach the tick's trace first: the worker (a thread, via
        # to_thread) would otherwise inherit it through the contextvar and
        # race the tick thread's unsynchronized span stack with its
        # per-symbol REST-call spans
        from binquant_tpu.obs.tracing import detached

        with detached():
            self._calibration_task = loop.create_task(_job())

    # -- breadth-derived inputs ----------------------------------------------

    def _breadth_scalars(self) -> tuple[float, float, float, float, float]:
        return breadth_scalars(self.market_breadth)

    # -- the tick -------------------------------------------------------------

    async def process_tick(self, now_ms: int | None = None) -> list:
        """One tick of the pipelined production loop.

        Dispatches tick i to the device (batcher drain → jit'd step → async
        wire D2H) and emits the oldest tick whose pipeline slot it evicts —
        with ``pipeline_depth=0`` that is tick i itself (serial fallback);
        with the live ``depth=1`` it is tick i-1, whose wire landed during
        the idle gap since the previous call, so nothing here blocks on the
        device round trip. ``latency['tick_total']`` therefore measures the
        true per-tick wall time of the production loop — the number the
        p99 < 50 ms budget is judged against. Returns the emitted signals
        (each stamped with ``tick_ms`` of the tick that produced it).
        """
        t_tick0 = time.perf_counter()
        # serial occupancy accounting: one "chunk" per call (this call's
        # phase brackets — finalize halves of evicted ticks + the new
        # dispatch — diffed against its wall clock)
        self.host_phase.begin_chunk("serial")
        fired: list = []
        # Finalize BEFORE dispatching: at depth 1 this consumes tick i-1's
        # (already-landed) wire first, so the host carries feeding tick i
        # (quiet-hours regime, grid-only policy) have the SAME one-tick lag
        # as the serial path — the semantics the pandas oracle verifies.
        # Dispatch-first would leave them two ticks stale.
        while len(self._pending) >= max(self.pipeline_depth, 1):
            fired.extend(await self._finalize_tick(self._pending.popleft()))
        pending = await self._dispatch_tick(now_ms)
        self._pending.append(pending)
        if self.pipeline_depth == 0:
            fired.extend(await self._finalize_tick(self._pending.popleft()))
        tick_wall_ms = (time.perf_counter() - t_tick0) * 1000.0
        self.latency.record("tick_total", tick_wall_ms)
        self.host_phase.note_chunk("serial", tick_wall_ms, 1)
        self.latency.maybe_log()
        self.ticks_processed += 1
        self._last_tick_wall_s = time.time()
        TICKS.inc()
        # event-log records carry the tick they were emitted under
        get_event_log().tick = self.ticks_processed
        self.touch_heartbeat()
        return fired

    async def flush_pending(self) -> list:
        """Finalize every in-flight tick (replay end, pre-checkpoint, or
        shutdown) so no dispatched tick's signals are lost."""
        fired: list = []
        while self._pending:
            fired.extend(await self._finalize_tick(self._pending.popleft()))
        # drain the background calibration worker too: replay results and
        # shutdown state must not depend on a task still in flight
        task = self._calibration_task
        if task is not None and not task.done():
            await task
        return fired

    async def aclose_delivery(self, drain_s: float = 5.0) -> None:
        """Gracefully retire the delivery plane (replay end / shutdown):
        best-effort drain, stop the workers, compact the WAL. Entries a
        down sink never acked stay durable for the next boot — this NEVER
        rides the tick path (flush_pending deliberately does not drain
        the plane; a sink outage must not stall the tick thread)."""
        if self.delivery is not None and self.delivery.started:
            await self.delivery.aclose(drain_s=drain_s)

    async def aclose_fanout(self) -> None:
        """Retire the fan-out plane: stop the hub (if served), emit the
        fanout_summary scoreboard, close the outbox."""
        if self.fanout is not None:
            await self.fanout.aclose()

    async def emit_ready(self) -> list:
        """Fired-tick fast path: land and emit the oldest in-flight tick
        NOW instead of waiting for the next tick to evict it.

        At depth 1 the pipelined loop otherwise emits tick i's signals a
        full cadence (~1 s) later; this waits out only the device round
        trip. The wire is landed in a worker thread so the event loop (WS
        ingest, Telegram sends) never blocks on the transfer; finalize
        order — and therefore the host-carry lag the A/B oracle pins — is
        unchanged, signals just leave earlier in wall time.
        """
        if not self._pending:
            return []
        head = self._pending[0]
        try:
            await asyncio.to_thread(np.asarray, head.wire)
        except Exception:
            logging.exception("early-emit wire landing failed; deferring")
            return []
        if self._pending and self._pending[0] is head:
            self._pending.popleft()
            return await self._finalize_tick(head)
        return []

    # -- scanned multi-tick drive (ISSUE 5) ----------------------------------
    #
    # Historical-data lanes (replay, A/B oracle drives, refdiff, restore
    # catch-up, backtesting) used to pay one Python loop iteration + one
    # device dispatch PER TICK even though their device compute is a
    # fraction of that. process_ticks_scanned partitions the recorded
    # stream into maximal clean-append runs and dispatches each run as ONE
    # jit'd lax.scan (engine/step.py tick_step_scan) — the EngineState
    # threads through the scan without returning to the host — then decodes
    # the stacked wires tick-by-tick through the standard finalize path
    # (emission, dedupe, policy, notifier, calibration: one copy of the
    # semantics). Chunk-break rules: cold start, mid-history rewrites,
    # registry churn, backfill folds, drift-audit ticks, and classic-path
    # engines (BQT_INCREMENTAL=0) all route through the per-tick path.

    # runs shorter than this re-enter the serial path (a scan compile is
    # not worth a handful of ticks)
    _SCAN_MIN_TICKS = 4

    async def process_ticks_scanned(self, ticks) -> list:
        """Drive a sequence of replayed ticks, fusing eligible runs.

        ``ticks`` iterates ``(now_ms, feed)`` pairs where ``feed`` is either
        a list of kline dicts (ingested one by one) or a zero-arg callable
        that loads the batchers itself (the bench's vectorized
        ``add_batch`` path). Returns every emitted signal, in tick order,
        each stamped with its producing ``tick_ms`` — the same contract as
        a serial ``process_tick`` loop, and (by construction plus the
        overflow re-run below) the identical signal set."""
        fired_all: list = []
        # in-flight ticks from BEFORE this drive still belong to the
        # caller — a serial process_tick loop would have returned them too
        fired_all.extend(await self.flush_pending())
        plan: dict | None = None
        for now_ms, feed in ticks:
            t_plan0 = time.perf_counter()
            if callable(feed):
                feed()
            else:
                for k in feed:
                    self.ingest(k)
            version0 = self.registry.version
            ingest_mono = self._oldest_pending_mono()
            batches5 = self.batcher5.drain()
            batches15 = self.batcher15.drain()
            churn = self.registry.version != version0
            if churn:
                # same rule as the serial drain: the new row's carry needs
                # a full-recompute re-anchor, and the requeued per-tick
                # dispatch below won't see the version change (the rows
                # were claimed by THIS drain)
                self._mark_carry_desynced("churn")
            clean = self._note_applied(batches5, batches15, commit=False)
            planned = 0 if plan is None else len(plan["ticks"])
            seq = self.ticks_processed + planned
            audit_due = (
                self.carry_audit_every > 0
                and seq > 0
                and seq % self.carry_audit_every == 0
            )
            scannable = (
                self.incremental
                and self.mesh is None
                and clean
                and not churn
                and self._carry_desync_reason is None
                and not audit_due
            )
            if not scannable:
                if plan is not None:
                    fired_all.extend(await self._flush_scan_plan(plan))
                    plan = None
                # the per-tick path re-judges cleanliness itself — hand the
                # drained sub-batches back (prebuilt batches drain in order)
                self._requeue_batches(batches5, batches15)
                fired_all.extend(await self.process_tick(now_ms=now_ms))
                continue
            if plan is None:
                plan = self._begin_scan_plan()
            self._note_applied(batches5, batches15)
            # grid-policy momentum is judged on the breadth the PREVIOUS
            # finalize saw (refresh below happens at this tick's dispatch)
            momentum_ok = self._grid_momentum_ok()
            bucket15 = (now_ms // 1000) // FIFTEEN_MIN_S
            await self._refresh_market_breadth(bucket15)
            plan["ticks"].append(
                self._plan_scan_tick(
                    now_ms, batches5, batches15, momentum_ok,
                    ingest_mono=ingest_mono,
                )
            )
            # per-tick planning dwell (feed, drain, eligibility judgments,
            # the plan snapshot) accumulates on the plan and lands as the
            # chunk's "plan" phase at flush
            plan["plan_ms"] += (time.perf_counter() - t_plan0) * 1000.0
            if len(plan["ticks"]) >= self.scan_chunk:
                fired_all.extend(await self._flush_scan_plan(plan))
                plan = None
        if plan is not None:
            fired_all.extend(await self._flush_scan_plan(plan))
        return fired_all

    async def process_ticks_backtest(
        self, ticks, params=None, chunk=None
    ) -> list:
        """Drive replayed ticks through the time-batched backtest backend
        (full-recompute semantics over (S, W+T) extended buffers; see
        binquant_tpu/backtest). Same contract as process_ticks_scanned."""
        from binquant_tpu.backtest.driver import drive_ticks_backtest

        return await drive_ticks_backtest(
            self, ticks, params=params, chunk=chunk
        )

    def _begin_scan_plan(self) -> dict:
        """Plan-start snapshots: enough host state to re-judge the run's
        ticks serially (overflow re-runs, too-short runs). The DEVICE
        anchor needs no snapshot — nothing dispatches while a plan
        accumulates, so ``self.state`` still holds the pre-chunk state at
        flush time (the scan dispatch is deliberately not donated)."""
        return {
            "ticks": [],
            "host_latest": {
                key: arr.copy() for key, arr in self._host_latest.items()
            },
            # ingest-monitor rewind anchor: an overflow re-drive replays
            # the plan's ticks through _note_applied a second time — the
            # per-symbol counters must stay exactly-once (obs/ingest.py)
            "ingest_monitor": self._begin_plan_ingest_state(),
            # accumulated per-tick planning dwell (host-phase "plan")
            "plan_ms": 0.0,
        }

    def _requeue_batches(self, batches5: list, batches15: list) -> None:
        for b in batches5:
            self.batcher5.add_batch(*b)
        for b in batches15:
            self.batcher15.add_batch(*b)

    def _grid_momentum_ok(self) -> bool:
        """Host half of the grid-only ladder (``GridOnlyPolicy.resolve``):
        is a non-flat breadth-momentum reading available? The regime half
        is recomputed per tick device-side inside the scan."""
        from binquant_tpu.regime.grid_policy import read_breadth_momentum

        momentum = read_breadth_momentum(self.market_breadth)
        return momentum is not None and momentum.leaning != "flat"

    def _oldest_pending_mono(self) -> float | None:
        """perf_counter of the oldest candle waiting in either batcher —
        read BEFORE draining (drain resets the stamps)."""
        stamps = [
            m
            for m in (
                self.batcher5.first_pending_mono,
                self.batcher15.first_pending_mono,
            )
            if m is not None
        ]
        return min(stamps) if stamps else None

    def _plan_scan_tick(
        self, now_ms: int, batches5: list, batches15: list, momentum_ok: bool,
        ingest_mono: float | None = None,
    ) -> _ScanTickPlan:
        ts_s = now_ms // 1000
        bucket15 = ts_s // FIFTEEN_MIN_S
        oi = None
        if self.oi_cache.has_data:
            oi = np.full(self.capacity, np.nan, dtype=np.float32)
            for rows, _, _ in batches15:
                for row in rows:
                    symbol = self.registry.name_of(int(row))
                    if symbol:
                        oi[int(row)] = self.oi_cache.growth(symbol)
        settings = self.at_consumer.autotrade_settings
        _btc = self.registry.row_of(self.btc_symbol)
        return _ScanTickPlan(
            now_ms=now_ms,
            ts5=(ts_s // FIVE_MIN_S) * FIVE_MIN_S - FIVE_MIN_S,
            ts15=bucket15 * FIFTEEN_MIN_S - FIFTEEN_MIN_S,
            bucket15=bucket15,
            batches5=batches5,
            batches15=batches15,
            momentum_ok=momentum_ok,
            breadth=self.market_breadth,
            tracked=self.registry.active_rows,
            oi=oi,
            adp=self._breadth_scalars(),
            quiet=bool(
                is_quiet_hours(now=datetime.fromtimestamp(now_ms / 1000, tz=UTC))
            ),
            btc_row=-1 if _btc is None else int(_btc),
            rows=self.registry.frozen_rows(),
            is_futures=str(settings.market_type).lower().endswith("futures"),
            dominance_is_losers=bool(
                getattr(
                    self.at_consumer, "current_market_dominance_is_losers", False
                )
            ),
            market_domination_reversal=bool(
                self.at_consumer.market_domination_reversal
            ),
            ingest_mono=ingest_mono,
        )

    async def _redrive_serial(self, plan: dict) -> list:
        """Run a plan's ticks through the standard per-tick path (runs too
        short to scan; overflow re-runs). The latest-ts mirror is restored
        to its plan-start snapshot first so the serial pass re-judges every
        tick exactly as the original stream did — each stays on the
        incremental route, keeping the emitted set identical to a
        never-scanned drive. Each tick also dispatches with ITS OWN
        plan-time ``tracked`` snapshot (not the live registry mask): a
        churn break drains the registry claim BEFORE the re-drive runs,
        so without the snapshot the re-driven ticks would read ``tracked``
        one claim early — zero signal impact (an empty row cannot fire)
        but a spurious per-tick diff in the ingest digest's tracked
        count (the PR 16 wrinkle, now closed)."""
        self._host_latest = {
            key: arr.copy() for key, arr in plan["host_latest"].items()
        }
        self.ingest_monitor.restore_state(plan.get("ingest_monitor"))
        fired: list = []
        for p in plan["ticks"]:
            self._requeue_batches(p.batches5, p.batches15)
            self._tracked_override = p.tracked
            try:
                fired.extend(await self.process_tick(now_ms=p.now_ms))
            finally:
                self._tracked_override = None
        return fired

    async def _flush_scan_plan(self, plan: dict) -> list:
        ticks = plan["ticks"]
        if not ticks:
            return []
        if len(ticks) < self._SCAN_MIN_TICKS or self.mesh is not None:
            return await self._redrive_serial(plan)
        # signals from still-pending serial ticks belong in the returned
        # stream too (depth>=1 engines)
        fired_all: list = await self.flush_pending()

        from binquant_tpu.engine.buffer import NUM_FIELDS

        bucket = _pow2_bucket
        T = len(ticks)
        n_slots = [max(len(p.batches5), len(p.batches15), 1) for p in ticks]
        depth = max(n_slots)
        u5_rows = bucket(
            max((len(b[0]) for p in ticks for b in p.batches5), default=1)
        )
        u15_rows = bucket(
            max((len(b[0]) for p in ticks for b in p.batches15), default=1)
        )
        tb = bucket(T)
        S = self.capacity

        key = self._wire_enabled_key()
        self._tick_seq += 1
        trace = self.tracer.begin_tick(self._tick_seq, tick_ms=ticks[-1].now_ms)
        trace.set_attr(path="scanned")
        # chunk-phase dwell (ISSUE 11): the accumulated per-tick planning
        # dwell lands as the chunk's "plan" phase (a synthetic span laid
        # just before the chunk — planning really happened interleaved
        # with the caller's feed loop), then stack/dispatch/device_wait
        # are live brackets, and the finalize loop closes the accounting.
        self.host_phase.begin_chunk("scanned")
        plan_ms = float(plan.get("plan_ms", 0.0))
        self.host_phase.record("scanned", "plan", plan_ms)
        t_chunk0 = time.perf_counter()
        if plan_ms:
            trace.record_span(
                "plan", t_chunk0 - plan_ms / 1000.0, t_chunk0,
                accumulated=True, ticks=T,
            )
        try:
            with self.latency.stage("scan_chunk"), trace.span(
                "scan_chunk", ticks=T, padded=tb, depth=depth,
            ), trace.activate():
                with trace.span("stack"), self.host_phase.phase(
                    "scanned", "stack"
                ):
                    r5 = np.full((tb, depth, u5_rows), -1, np.int32)
                    t5 = np.full((tb, depth, u5_rows), -1, np.int32)
                    v5 = np.zeros(
                        (tb, depth, u5_rows, NUM_FIELDS), np.float32
                    )
                    r15 = np.full((tb, depth, u15_rows), -1, np.int32)
                    t15 = np.full((tb, depth, u15_rows), -1, np.int32)
                    v15 = np.zeros(
                        (tb, depth, u15_rows, NUM_FIELDS), np.float32
                    )
                    for i, p in enumerate(ticks):
                        # serial pairing preserved: the tick's own slots
                        # sit at the TAIL (front-padded with exact-no-op
                        # empties), so its last slot is always the
                        # evaluated one — _fold_updates semantics
                        off = depth - n_slots[i]
                        for d, b in enumerate(p.batches5):
                            r5[i, off + d], t5[i, off + d], v5[i, off + d] = (
                                pad_updates(*b, size=u5_rows)
                            )
                        for d, b in enumerate(p.batches15):
                            r15[i, off + d], t15[i, off + d], v15[i, off + d] = (
                                pad_updates(*b, size=u15_rows)
                            )
                    inputs_seq, active, momentum_seq = (
                        self._stack_plan_inputs(ticks, tb)
                    )
                    policy_prev = (
                        np.bool_(self._last_regime is not None),
                        np.int32(
                            -1 if self._last_regime is None
                            else self._last_regime
                        ),
                    )
                t_launch0 = time.perf_counter()
                with trace.span("dispatch"), self.host_phase.phase(
                    "scanned", "dispatch"
                ):
                    is_new_sig = observe_dispatch(
                        self.state, (r5, t5, v5), (r15, t15, v15), key,
                        cfg=self.context_config, fn="tick_step_scan",
                        incremental=True, maintain_carry=True,
                        numeric_digest=self.numeric_digest,
                        ingest_digest=self.ingest_digest,
                    )
                    scan_sig = (
                        f"{self._ledger_sig((r5,), (r15,), True)}"
                        f" T{tb}xD{depth}"
                    )
                    cost_fn = None
                    if is_new_sig:
                        a_args, _ = abstract_args(
                            (
                                self.state, (r5, t5, v5), (r15, t15, v15),
                                inputs_seq, active, momentum_seq, policy_prev,
                            )
                        )
                        cfg_, dig_ = self.context_config, self.numeric_digest
                        ing_ = self.ingest_digest

                        def cost_fn(args=a_args):
                            return lowered_cost(
                                tick_step_scan, *args, cfg_,
                                wire_enabled=key, incremental=True,
                                maintain_carry=True, numeric_digest=dig_,
                                ingest_digest=ing_,
                            )

                    # NOT donated: self.state stays alive as the pre-chunk
                    # anchor the overflow re-run below rewinds to
                    with LEDGER.watch(
                        "tick_step_scan", scan_sig, expect_compile=is_new_sig,
                        cost_fn=cost_fn, tick=self.ticks_processed,
                    ):
                        new_state, wires_dev, _counts = tick_step_scan(
                            self.state,
                            (r5, t5, v5),
                            (r15, t15, v15),
                            inputs_seq,
                            active,
                            momentum_seq,
                            policy_prev,
                            self.context_config,
                            wire_enabled=key,
                            incremental=True,
                            maintain_carry=True,
                            numeric_digest=self.numeric_digest,
                            ingest_digest=self.ingest_digest,
                        )
                with trace.span("device_wait"), self.host_phase.phase(
                    "scanned", "device_wait"
                ):
                    wires = np.asarray(wires_dev)
        except BaseException as exc:
            trace.mark_error(exc)
            self.tracer.complete(trace, snapshot_fn=self._flight_snapshot)
            raise
        # chunk-level dispatch→wire-fetch freshness, measured from the
        # LAUNCH (stack packing excluded — comparable with the serial
        # drive's stamp; the per-tick finalize fetches below read an
        # already-landed host array)
        self.freshness.observe_stage(
            "dispatch_to_fetch", (time.perf_counter() - t_launch0) * 1000.0
        )
        counts = wires[:T, WIRE_FIRED_COUNT_OFF]
        if np.any(counts > WIRE_MAX_FIRED):
            # a tick's fired set overflowed the wire's compaction slots:
            # drop the chunk's outputs on the floor (self.state was never
            # advanced) and re-drive serially — the per-tick path runs its
            # audited overflow fallback, so the emitted set stays exact
            trace.set_attr(overflow_rerun=True)
            self.tracer.complete(trace, snapshot_fn=self._flight_snapshot)
            # close the chunk's occupancy accounting: the host really
            # spent this wall even though the outputs are discarded (and
            # an open chunk must not linger into the serial re-drive)
            self.host_phase.note_chunk(
                "scanned",
                plan_ms + (time.perf_counter() - t_chunk0) * 1000.0,
                T,
            )
            self.scan_overflow_reruns += 1
            SCAN_OVERFLOW_RERUNS.inc()
            fired_all.extend(await self._redrive_serial(plan))
            return fired_all
        self.state = new_state
        self.scan_chunks += 1
        SCAN_CHUNKS.inc()

        # batch decode (ISSUE 17): one vectorized pass over the landed
        # (T, L) wire block replaces T per-tick unpack_wire re-slices —
        # finalize consumes the pre-decoded (WireFired, ctx) tuples
        from binquant_tpu.engine.step import unpack_wire_block

        t_dec0 = time.perf_counter()
        seq = unpack_wire_block(
            wires[:T], numeric_digest=self.numeric_digest,
            ingest_digest=self.ingest_digest,
        )
        self.host_phase.record(
            "scanned", "decode", (time.perf_counter() - t_dec0) * 1000.0
        )

        per_tick_ms = (time.perf_counter() - t_chunk0) * 1000.0 / T
        t_fin0 = time.perf_counter()
        try:
            for i, p in enumerate(ticks):
                # finalize reads the breadth this tick's dispatch saw
                self.market_breadth = p.breadth
                pending = _PendingTick(
                    wire=wires[i],
                    fallback=_scan_fallback_unavailable,
                    ts_ms=p.now_ms,
                    ts5=p.ts5,
                    ts15=p.ts15,
                    bucket15=p.bucket15,
                    dispatched_at=t_chunk0,
                    rows=p.rows,
                    trace=NULL_TRACE,
                    drive="scanned",
                    ingest_mono=p.ingest_mono,
                    unpacked=seq[i],
                )
                fired_all.extend(await self._finalize_tick(pending))
                self.latency.record("tick_total", per_tick_ms)
                self.ticks_processed += 1
                self._last_tick_wall_s = time.time()
                TICKS.inc()
                get_event_log().tick = self.ticks_processed
                self.incremental_ticks += 1
                self.scanned_ticks += 1
                SCANNED_TICKS.inc()
        finally:
            # the chunk trace closes AFTER its finalizes so the waterfall
            # shows the back-to-back decode/emit half, not just the
            # dispatch — and an errored finalize still flight-records
            trace.record_span("finalize", t_fin0, ticks=T)
            self.tracer.complete(trace, snapshot_fn=self._flight_snapshot)
            self.host_phase.note_chunk(
                "scanned",
                plan_ms + (time.perf_counter() - t_chunk0) * 1000.0,
                T,
            )
        self.touch_heartbeat()
        return fired_all

    def _stack_plan_inputs(self, ticks: list, tb: int):
        """Stacked (tb, ...) HostInputs + active/momentum vectors from a
        list of _ScanTickPlan — the ONE copy of the per-tick host-input
        stacking both multi-tick backends share (the scanned lax.scan
        chunks and the time-batched backtest chunks).
        ``grid_policy_allows`` is zeroed: both backends recompute it
        device-side per tick from their policy carry."""
        from binquant_tpu.engine.step import HostInputs

        T = len(ticks)
        S = self.capacity
        nan_oi = np.full((S,), np.nan, dtype=np.float32)
        no_rows = np.zeros((S,), np.bool_)
        inputs_seq = HostInputs(
            tracked=np.stack(
                [p.tracked for p in ticks] + [no_rows] * (tb - T)
            ),
            btc_row=self._stack_scalar(
                [p.btc_row for p in ticks], tb, np.int32, -1
            ),
            timestamp_s=self._stack_scalar(
                [p.ts15 for p in ticks], tb, np.int32, 0
            ),
            timestamp5_s=self._stack_scalar(
                [p.ts5 for p in ticks], tb, np.int32, 0
            ),
            oi_growth=np.stack(
                [p.oi if p.oi is not None else nan_oi for p in ticks]
                + [nan_oi] * (tb - T)
            ),
            adp_latest=self._stack_scalar(
                [p.adp[0] for p in ticks], tb, np.float32, np.nan
            ),
            adp_prev=self._stack_scalar(
                [p.adp[1] for p in ticks], tb, np.float32, np.nan
            ),
            adp_diff=self._stack_scalar(
                [p.adp[2] for p in ticks], tb, np.float32, np.nan
            ),
            adp_diff_prev=self._stack_scalar(
                [p.adp[3] for p in ticks], tb, np.float32, np.nan
            ),
            breadth_momentum_points=self._stack_scalar(
                [p.adp[4] for p in ticks], tb, np.float32, np.nan
            ),
            quiet_hours=self._stack_scalar(
                [p.quiet for p in ticks], tb, np.bool_, False
            ),
            # recomputed device-side per tick from the policy carry
            grid_policy_allows=np.zeros((tb,), np.bool_),
            is_futures=self._stack_scalar(
                [p.is_futures for p in ticks], tb, np.bool_, False
            ),
            dominance_is_losers=self._stack_scalar(
                [p.dominance_is_losers for p in ticks], tb, np.bool_, False
            ),
            market_domination_reversal=self._stack_scalar(
                [p.market_domination_reversal for p in ticks],
                tb, np.bool_, False,
            ),
        )
        active = np.zeros((tb,), np.bool_)
        active[:T] = True
        momentum_seq = self._stack_scalar(
            [p.momentum_ok for p in ticks], tb, np.bool_, False
        )
        return inputs_seq, active, momentum_seq

    @staticmethod
    def _stack_scalar(values: list, tb: int, dtype, fill) -> np.ndarray:
        out = np.full((tb,), fill, dtype=dtype)
        out[: len(values)] = np.asarray(values, dtype=dtype)
        return out

    async def _dispatch_tick(self, now_ms: int | None = None) -> _PendingTick:
        """Drain batchers and launch the jit'd step + async wire transfer.

        A dispatch-phase failure (fold, input build, the jit launch)
        completes the tick's trace as errored before propagating — those
        are exactly the ticks the flight recorder must capture, and no
        ``_PendingTick`` will ever carry this trace to finalize."""
        ts_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        # one trace per tick (or NULL_TRACE when sampled out); on success
        # it rides the _PendingTick and is completed — flight-recorder
        # check included — when the tick finalizes
        self._tick_seq += 1
        trace = self.tracer.begin_tick(self._tick_seq, tick_ms=ts_ms)
        try:
            return await self._dispatch_tick_inner(ts_ms, trace)
        except BaseException as exc:
            trace.mark_error(exc)
            self.tracer.complete(trace, snapshot_fn=self._flight_snapshot)
            raise


    async def _dispatch_tick_inner(self, ts_ms: int, trace) -> _PendingTick:
        import jax.numpy as jnp

        t_plan0 = time.perf_counter()
        ts_s = ts_ms // 1000
        # Evaluate against the bar that just CLOSED: its open time is one
        # full interval behind the current wall-clock bucket.
        bucket15 = ts_s // FIFTEEN_MIN_S
        ts15 = bucket15 * FIFTEEN_MIN_S - FIFTEEN_MIN_S
        ts5 = (ts_s // FIVE_MIN_S) * FIVE_MIN_S - FIVE_MIN_S

        with self.latency.stage("breadth_refresh"), trace.span("breadth_refresh"):
            await self._refresh_market_breadth(bucket15)

        with self.latency.stage("ingest_drain"), trace.span("ingest_drain") as sp_drain:
            # backlog at dispatch: how many deduped candles this tick drains
            QUEUE_DEPTH.labels(queue="batcher5").set(len(self.batcher5))
            QUEUE_DEPTH.labels(queue="batcher15").set(len(self.batcher15))
            # ingest-arrival anchor: the oldest candle THIS tick drains
            # (read before drain — drain resets the batcher stamps)
            ingest_mono = self._oldest_pending_mono()
            registry_version0 = self.registry.version
            batches5 = self.batcher5.drain()
            batches15 = self.batcher15.drain()
            if self.registry.version != registry_version0:
                # a NEW symbol claimed a row in this drain (listing wave /
                # reclaimed churn row): its carried indicator state was
                # initialized on whatever window the LAST full recompute
                # saw — an empty ring or a prior occupant's history — so
                # advancing it incrementally would diverge from a fresh
                # compute. Route one full recompute to re-anchor every
                # row's carry (at cold start the earlier cold_start reason
                # wins; the scanned drive breaks its chunk on the same
                # version change, keeping both drives' routing identical).
                self._mark_carry_desynced("churn")
            # incremental-path eligibility: every update this tick must be
            # a clean strictly-newer append, judged against the host-side
            # latest-ts mirror (a mid-history rewrite is invisible to the
            # device-side carry — the window's interior changes without
            # the latest bar moving)
            clean_appends = self._note_applied(batches5, batches15)
            sp_drain.set(
                batches5=len(batches5),
                batches15=len(batches15),
                clean_appends=clean_appends,
            )
            if not clean_appends:
                self._mark_carry_desynced("rewrite")
            # OI growth for symbols with fresh 15m candles (reference
            # cadence). Cache-only reads: the background refresh_forever
            # loop owns the REST traffic — a 15m boundary with 2000 fresh
            # symbols performs zero network calls here. O(cached symbols),
            # not O(capacity): an empty cache (spot deployments, bench)
            # reuses one device-resident all-NaN array.
            oi = None
            if self.oi_cache.has_data:
                oi = np.full(self.capacity, np.nan, dtype=np.float32)
                for rows, _, _ in batches15:
                    for row in rows:
                        symbol = self.registry.name_of(int(row))
                        if symbol:
                            oi[int(row)] = self.oi_cache.growth(symbol)

        adp_latest, adp_prev, adp_diff, adp_diff_prev, momentum = (
            self._breadth_scalars()
        )
        settings = self.at_consumer.autotrade_settings
        # Quiet-hours: the host resolves only the wall-clock WINDOW; the
        # strong-stable-trend override is applied device-side inside
        # tick_step from the context computed THIS tick — the reference's
        # exact semantics (time_of_day_filter.py:60-76 reads the live
        # context). The window reads the EVALUATED tick time, not the wall
        # clock — identical live (tick time ≈ now), and it makes replays
        # deterministic instead of depending on when they happen to run.
        quiet = is_quiet_hours(
            now=datetime.fromtimestamp(ts_ms / 1000, tz=UTC)
        )
        # row 0 is a valid registry row — `or -1` would misread it as missing
        _btc = self.registry.row_of(self.btc_symbol)
        btc_row = -1 if _btc is None else int(_btc)

        # Resolve this tick's evaluation path. The drift audit fires on the
        # engine's own tick counter so replay determinism is preserved
        # (same stream → same audit ticks).
        audit_due = (
            self.carry_audit_every > 0
            and self.ticks_processed > 0
            and self.ticks_processed % self.carry_audit_every == 0
        )
        with trace.span("route_decision") as sp_route:
            if not self.incremental:
                use_incremental, reason = False, None
            elif self._carry_desync_reason is not None:
                use_incremental, reason = False, self._carry_desync_reason
            elif audit_due:
                use_incremental, reason = False, "audit"
            else:
                use_incremental, reason = True, None
            if self.incremental:
                if use_incremental:
                    self.incremental_ticks += 1
                else:
                    self.full_recompute_ticks += 1
                    FULL_RECOMPUTE.labels(reason=reason).inc()
                    self.full_recompute_reasons[reason] = (
                        self.full_recompute_reasons.get(reason, 0) + 1
                    )
            path = "incremental" if use_incremental else "full"
            sp_route.set(path=path, full_recompute_reason=reason)
            # root attr: the ring summary / healthz "carry path taken"
            trace.set_attr(path=path if reason is None else f"{path}:{reason}")
        # serial dispatch-half dwell: plan covers breadth refresh, drain,
        # and routing; stack covers the audit/fold/input build below
        self.host_phase.record(
            "serial", "plan", (time.perf_counter() - t_plan0) * 1000.0
        )
        t_stack0 = time.perf_counter()

        # explicit params override (backtest drives) — None stays the
        # baked-constant live graph. Resolved before the drift meter so an
        # audit tick under custom params compares carry twins built with
        # the SAME thresholds.
        if self.strategy_params is None:
            sp_arg = None
        else:
            from binquant_tpu.strategies.params import dynamic_params

            sp_arg = dynamic_params(self.strategy_params)

        # Carry-drift audit meter (ISSUE 7): on an audit tick, measure the
        # per-family gap between the carried state advanced by THIS tick's
        # updates — replaying the exact carry-advancing folds the
        # incremental path would have run, on a FUNCTIONAL copy that never
        # touches self.state — and a fresh full-recompute init from the
        # same post-update windows, BEFORE the full dispatch below resyncs
        # the carry. Costs (slots-1) fold dispatches + one measurement
        # dispatch per audit tick (every BQT_CARRY_AUDIT_EVERY ticks).
        if reason == "audit" and self.drift_meter_enabled:
            try:
                with self.latency.stage("carry_audit"), trace.span(
                    "carry_audit"
                ) as sp_audit:
                    empty = self._empty_updates()
                    slots5 = [pad_updates(*b) for b in batches5] or [empty]
                    slots15 = [pad_updates(*b) for b in batches15] or [empty]
                    n = max(len(slots5), len(slots15))
                    st = self.state
                    for i in range(n - 1):
                        st = apply_updates_carry_step(
                            st,
                            slots5[i] if i < len(slots5) else empty,
                            slots15[i] if i < len(slots15) else empty,
                            btc_row=btc_row,
                        )
                    # the measured args resolved ONCE: the ledger watch's
                    # signature must name the buckets actually dispatched
                    # (the shorter interval's final slot is the padded
                    # empty, not its own last batch)
                    mu5 = slots5[-1] if len(slots5) == n else empty
                    mu15 = slots15[-1] if len(slots15) == n else empty
                    # any residual compile (a bucket the pre-warm below
                    # missed) is at least attributed on the ledger
                    with LEDGER.watch(
                        "carry_drift_meter",
                        self._ledger_sig(mu5, mu15, True),
                        expect_compile=False,
                        tick=self.ticks_processed,
                    ):
                        drift = measure_carry_drift(
                            st, mu5, mu15, btc_row, params=sp_arg
                        )
                    breached = self.drift.observe(
                        drift,
                        tick_ms=ts_ms,
                        trace_id=trace.trace_id,
                        snapshot_fn=self._flight_snapshot,
                    )
                    sp_audit.set(
                        breached=len(breached),
                        **{
                            f"drift_{fam}": v["max_abs"]
                            for fam, v in drift.items()
                        },
                    )
            except Exception:
                # metering must never take down the tick — the audit's
                # resync below proceeds either way
                self.drift.note_skipped()
                logging.exception("carry-drift metering failed; audit "
                                  "proceeds unmeasured")

        # Drift-meter pre-warm: the measurement's jit entry (carry advance
        # + full-window init + the comparison reductions) would otherwise
        # compile SYNCHRONOUSLY inside the first audit tick — a
        # multi-second stall on exactly the path the meter instruments.
        # Warm it in the background on a THROWAWAY same-shape state (the
        # jit cache keys on shapes; real state must not leak to a thread
        # that could outlive the next donation), once per update-bucket
        # shape, the first time that shape appears on an incremental tick.
        if (
            self.drift_meter_enabled
            and use_incremental
            and not self.config.is_ci
        ):
            # mirror the audit block's measured-arg resolution exactly:
            # the measurement runs on each interval's LAST slot — which is
            # the (4,)-padded empty slot when that interval has fewer
            # sub-batches than the other — NOT the per-tick max bucket (a
            # max-bucket warm would miss the audit's actual shape and the
            # synchronous compile this block exists to prevent would run
            # inside the audit tick anyway)
            n_slots = max(len(batches5) or 1, len(batches15) or 1)

            def _measured_bucket(batches):
                if not batches or (len(batches) or 1) != n_slots:
                    return 4  # the padded _empty_updates slot
                m = len(batches[-1][0])
                size = 1
                while size < max(m, 1):
                    size *= 2
                return size

            wsig = (_measured_bucket(batches5), _measured_bucket(batches15))
            if wsig not in self._drift_warmed:
                self._drift_warmed.add(wsig)
                import threading

                def _warm_drift(s5=wsig[0], s15=wsig[1]):
                    try:
                        st = initial_engine_state(
                            self.capacity, window=self.window
                        )
                        e5 = pad_updates(
                            np.zeros(0, np.int32), np.zeros(0, np.int32),
                            np.zeros((0, 10), np.float32), size=s5,
                        )
                        e15 = pad_updates(
                            np.zeros(0, np.int32), np.zeros(0, np.int32),
                            np.zeros((0, 10), np.float32), size=s15,
                        )
                        with LEDGER.watch(
                            "carry_drift_meter",
                            f"S{self.capacity}xW{self.window} "
                            f"u5[{s5}] u15[{s15}] warm",
                            expect_compile=True,
                        ):
                            measure_carry_drift(st, e5, e15, -1)
                    except Exception:
                        logging.exception(
                            "drift-meter pre-warm failed (non-fatal)"
                        )

                threading.Thread(target=_warm_drift, daemon=True).start()

        # Ordered sub-batch replay: fold all but the FINAL sub-batch into
        # the buffers, then run ONE full evaluation on the final state.
        # On the fast path the folds advance the carry too, so multi-bar
        # clean-append drains stay incremental.
        with trace.span("buffer_fold", advance_carry=use_incremental):
            u5, u15 = self._fold_updates(
                batches5, batches15, advance_carry=use_incremental,
                btc_row=btc_row,
            )
        t_inputs0 = time.perf_counter()
        if self._base_inputs is None:
            self._base_inputs = default_host_inputs(self.capacity)
            if self.mesh is not None:
                from binquant_tpu.parallel.mesh import shard_host_inputs

                self._base_inputs = shard_host_inputs(
                    self._base_inputs, self.mesh
                )
        if oi is None:
            if self._nan_oi_cache is None:
                self._nan_oi_cache = self._place_symbol_array(
                    np.full((self.capacity,), np.nan, dtype=np.float32)
                )
            oi_dev = self._nan_oi_cache
        else:
            oi_dev = self._place_symbol_array(oi)
        inputs = self._base_inputs._replace(
            tracked=self._tracked_mask(),
            btc_row=np.int32(btc_row),
            timestamp_s=np.int32(ts15),
            timestamp5_s=np.int32(ts5),
            oi_growth=oi_dev,
            adp_latest=self._dev_scalar("adp_latest", np.float32(adp_latest)),
            adp_prev=self._dev_scalar("adp_prev", np.float32(adp_prev)),
            adp_diff=self._dev_scalar("adp_diff", np.float32(adp_diff)),
            adp_diff_prev=self._dev_scalar(
                "adp_diff_prev", np.float32(adp_diff_prev)
            ),
            breadth_momentum_points=self._dev_scalar(
                "breadth_momentum", np.float32(momentum)
            ),
            quiet_hours=self._dev_scalar("quiet_hours", bool(quiet)),
            grid_policy_allows=self._dev_scalar(
                "grid_policy_allows", bool(self.grid_only_policy.allow_grid_ladder)
            ),
            is_futures=self._dev_scalar(
                "is_futures",
                str(settings.market_type).lower().endswith("futures"),
            ),
            # host-resolved market-domination state: attrs on the consumer
            # (reference pattern, context_evaluator.py:95-97 /
            # autotrade_consumer.py:37) — NEUTRAL/False in production,
            # scriptable in replay so the dominance-gated strategies can
            # be A/B'd
            dominance_is_losers=self._dev_scalar(
                "dominance_is_losers",
                bool(
                    getattr(
                        self.at_consumer,
                        "current_market_dominance_is_losers",
                        False,
                    )
                ),
            ),
            market_domination_reversal=self._dev_scalar(
                "market_domination_reversal",
                bool(self.at_consumer.market_domination_reversal),
            ),
        )
        self.latency.record(
            "inputs_build", (time.perf_counter() - t_inputs0) * 1000.0
        )
        trace.record_span("inputs_build", t_inputs0)
        self.host_phase.record(
            "serial", "stack", (time.perf_counter() - t_stack0) * 1000.0
        )
        t_dispatch0 = time.perf_counter()
        mode = self._donation_mode()
        donate = mode is not None
        with self.latency.stage("device_dispatch"), trace.span(
            "device_dispatch", incremental=use_incremental, donated=donate
        ), trace.activate():
            # Wire-only step: the full TickOutputs pytree is ~400 output
            # buffers whose handle creation dominates dispatch (measured
            # ~6.6 ms vs ~2.9 ms at S=2048 through the tunneled chip). The
            # host consumes only the wire; the rare overflow/payload-less
            # paths re-run the full step via the fallback closure below.
            prev_state = self.state
            small = _snapshot_small_carries(prev_state) if donate else None
            scratch = None
            if mode == "double":
                # rotate a free slot in; a fresh zeros state covers boot
                # (no tick has finalized yet) and pool misses
                scratch = (
                    self._spare_slots.pop() if self._spare_slots else None
                )
                if scratch is None or scratch is prev_state:
                    scratch = self._fresh_state()
                # donation rejects internally-aliased buffers (zero-fill
                # dedup in a fresh state, XLA output dedup in a recycled
                # one) — split them before handing the slot over
                scratch = _unique_buffers(scratch)
            # ONE source of truth per donation mode for the dispatched
            # function, its ledger/recompile-counter name, and its
            # positional args — the cost thunk below must lower exactly
            # the signature the launch executes
            from binquant_tpu.engine.step import tick_step_wire_db

            fn_name, step_fn = {
                "single": ("tick_step_wire_donated", tick_step_wire_donated),
                "double": ("tick_step_wire_db", tick_step_wire_db),
            }.get(mode, ("tick_step_wire", tick_step_wire))
            launch_args = (
                (prev_state, scratch, u5, u15, inputs)
                if mode == "double"
                else (prev_state, u5, u15, inputs)
            )
            # recompile counter + symbols-per-tick gauge (engine/step.py's
            # shape-signature cache — a True return means the launch below
            # pays a jax trace+compile, which the executable ledger then
            # times and costs)
            # ingest digest: the tick's accumulated fold counts ride the
            # dispatch as ONE stable (8,) dynamic arg (zeros template on
            # fold-free ticks; None compiles the pre-ingest graph)
            ing_counts = self._take_ingest_fold_counts()
            is_new_sig = observe_dispatch(
                prev_state, u5, u15, self._wire_enabled_key(),
                cfg=self.context_config,
                fn=fn_name,
                incremental=use_incremental,
                maintain_carry=self.incremental,
                numeric_digest=self.numeric_digest,
                ingest_digest=self.ingest_digest,
            )
            # StepTraceAnnotation groups this tick's XLA work in profiler
            # captures; skipped entirely on untraced ticks outside a
            # /debug/profile window (hot path stays annotation-free)
            step_ctx = (
                step_annotation(self._tick_seq)
                if trace.active or profiler_window_active()
                else contextlib.nullcontext()
            )
            ledger_sig = self._ledger_sig(u5, u15, use_incremental)
            cost_fn = None
            if is_new_sig:
                # cost thunk over ABSTRACT avals captured before the launch
                # can donate the state — lowering is a re-trace, not a
                # recompile, and runs on the ledger's background worker
                a_pos, _ = abstract_args(launch_args)
                cfg_, key_ = self.context_config, self._wire_enabled_key()
                incr_, maint_ = use_incremental, self.incremental
                dig_, ing_ = self.numeric_digest, self.ingest_digest
                a_ing = (
                    abstract_args((ing_counts,))[0][0]
                    if ing_counts is not None
                    else None
                )

                def cost_fn(fn=step_fn, a_pos=a_pos, a_ing=a_ing):
                    return lowered_cost(
                        fn, *a_pos, cfg_,
                        wire_enabled=key_, incremental=incr_,
                        maintain_carry=maint_, params=sp_arg,
                        numeric_digest=dig_,
                        ingest_digest=ing_,
                        ingest_fold_counts=a_ing,
                    )

            try:
                with LEDGER.watch(
                    fn_name, ledger_sig, expect_compile=is_new_sig,
                    cost_fn=cost_fn, tick=self.ticks_processed,
                ), step_ctx:
                    self.state, wire = step_fn(
                        *launch_args,
                        self.context_config,
                        # device-side wire compaction must match the host's
                        # enabled set
                        wire_enabled=self._wire_enabled_key(),
                        incremental=use_incremental,
                        # classic-path deployments (BQT_INCREMENTAL=0) never
                        # read the carry — skip its full-window re-init
                        maintain_carry=self.incremental,
                        params=sp_arg,
                        numeric_digest=self.numeric_digest,
                        ingest_digest=self.ingest_digest,
                        ingest_fold_counts=ing_counts,
                    )
            except BaseException:
                if mode == "single":
                    # a launch that failed AFTER consuming the donated
                    # buffers leaves no usable pre-tick state — detect and
                    # reset instead of crash-looping on deleted arrays
                    self._recover_after_donated_failure(prev_state)
                # "double": only the spare slot was consumed; prev_state
                # (still self.state) is intact — the slot re-allocates
                # at the next dispatch
                raise
            if donate:
                self.donated_ticks += 1
            if mode == "single":
                # the only live references to the donated buffers are gone
                # past this point — the audit the donated path relies on:
                # fallback/prewarm/checkpoint all read self.state (post)
                prev_state = None
            if not use_incremental:
                # the full step re-initialized the carry from the windows
                self._carry_desync_reason = None
            # start the wire's D2H immediately; by the time this tick is
            # finalized (depth ticks later) the transfer has landed and the
            # host-side np.asarray is a copy, not a round trip
            try:
                wire.copy_to_host_async()
            except AttributeError:
                pass  # non-jax array (tests with stubbed steps)

        cfg, key = self.context_config, self._wire_enabled_key()
        # the fallback re-evaluates with the SAME static variant the wire
        # step ran: full-window vs carried readouts differ by f32 epsilon,
        # and an overflow tick's emitted set must match the stream the
        # incremental path certified (numeric_digest rides along so the
        # fallback wire keeps the engine's layout)
        incr_args = (
            use_incremental, self.incremental, self.numeric_digest,
            self.ingest_digest,
        )

        if mode == "single":
            # Donated dispatch: the pre-tick buffers no longer exist, so
            # the fallback rebuilds this tick's evaluation from the
            # POST-tick buffers (updates only feed apply_updates, already
            # applied) + the pre-tick small-carry snapshots, with EMPTY
            # update batches. ``self.state`` is read lazily at CALL time —
            # correct because single-slot donation is only engaged at
            # depth<=1, where a tick always finalizes before the next
            # dispatch can donate the post state (_donation_mode).
            empty = self._empty_updates()

            def fallback(
                _args=(small, inputs, cfg, key, incr_args, empty, sp_arg)
            ):
                small_, inp, cfg_, key_, (incr_, maint_, dig_, ing_), emp, sp_ = _args
                st = self.state._replace(
                    regime_carry=small_[0],
                    mrf_last_emitted=small_[1],
                    pt_last_signal_close=small_[2],
                    indicator_carry=small_[3],
                )
                _, full = tick_step(
                    st, emp, emp, inp, cfg_, wire_enabled=key_,
                    incremental=incr_, maintain_carry=maint_, params=sp_,
                    numeric_digest=dig_, ingest_digest=ing_,
                )
                return full

            warm_sig = (key, "donated", empty[0].shape, incr_args)
        elif mode == "double":
            # Double-buffered dispatch at depth>=2: by the time this tick
            # finalizes, LATER dispatches have replaced self.state — so
            # the post state is captured EAGERLY (it is alive: the db step
            # donated only the scratch slot). Same empty-updates
            # re-evaluation from post buffers + pre-tick small carries as
            # the single-slot scheme; same jit cache entry (tick_step on
            # empty buckets), so one pre-warm covers both donation modes.
            empty = self._empty_updates()
            post_state = self.state

            def fallback(
                _args=(post_state, small, inputs, cfg, key, incr_args,
                       empty, sp_arg)
            ):
                post, small_, inp, cfg_, key_, incrs, emp, sp_ = _args
                incr_, maint_, dig_, ing_ = incrs
                st = post._replace(
                    regime_carry=small_[0],
                    mrf_last_emitted=small_[1],
                    pt_last_signal_close=small_[2],
                    indicator_carry=small_[3],
                )
                _, full = tick_step(
                    st, emp, emp, inp, cfg_, wire_enabled=key_,
                    incremental=incr_, maintain_carry=maint_, params=sp_,
                    numeric_digest=dig_, ingest_digest=ing_,
                )
                return full

            warm_sig = (key, "donated", empty[0].shape, incr_args)
        else:
            # NOTE the retention cost of the copying path: the closure pins
            # the pre-tick state (dominated by the ~66 MB of ring buffers
            # at production shape) in device memory until this tick
            # finalizes — one extra state copy per in-flight tick.

            def fallback(
                _args=(prev_state, u5, u15, inputs, cfg, key, incr_args,
                       sp_arg)
            ):
                st, upd5, upd15, inp, cfg_, key_, incrs, sp_ = _args
                incr_, maint_, dig_, ing_ = incrs
                _, full = tick_step(
                    st, upd5, upd15, inp, cfg_, wire_enabled=key_,
                    incremental=incr_, maintain_carry=maint_, params=sp_,
                    numeric_digest=dig_, ingest_digest=ing_,
                )
                return full

            warm_sig = (key, u5[0].shape, u15[0].shape, incr_args)

        # Pre-warm the fallback's jit cache in the background the first
        # time each (wire key, update-bucket shape) appears: without this,
        # the first overflow tick (>WIRE_MAX_FIRED fired pairs — a broad
        # market burst, exactly when signals matter) would pay the full
        # step's trace+compile (tens of seconds) inside finalize. One
        # throwaway execution per shape bucket (~60 ms device time).
        # The donated variant warms on a THROWAWAY empty state of the same
        # shapes — the jit cache keys on shapes/dtypes, and the real
        # fallback args must never leak into a background thread that
        # could still hold them when the next dispatch donates them.
        # (skipped under CI/replay stubs — a surprise compile there only
        # costs a test second, and the suite would otherwise pay a full
        # background compile per stub engine)
        if not self.config.is_ci and warm_sig not in self._fallback_warmed:
            self._fallback_warmed.add(warm_sig)
            import threading

            if donate:
                # the throwaway state matches the live one's placement —
                # under a mesh an unsharded warm state would compile (and
                # warm) a different executable than the real fallback uses
                warm_args = (
                    self._fresh_state(),
                    empty, empty, inputs, cfg, key, incr_args,
                )
            else:
                warm_args = (prev_state, u5, u15, inputs, cfg, key, incr_args)

            def _warm(args=warm_args, sp_=sp_arg,
                      sig_=f"{self._ledger_sig(u5, u15, use_incremental)} "
                           "fallback"):
                try:
                    st, upd5, upd15, inp, cfg_, key_, incrs = args
                    incr_, maint_, dig_, ing_ = incrs
                    # the ledger watch runs on THIS thread — compile events
                    # attribute to the fallback entry, not the tick's
                    with LEDGER.watch("tick_step", sig_, expect_compile=True):
                        tick_step(
                            st, upd5, upd15, inp, cfg_, wire_enabled=key_,
                            incremental=incr_, maintain_carry=maint_,
                            params=sp_, numeric_digest=dig_,
                            ingest_digest=ing_,
                        )
                except Exception:
                    logging.exception("fallback pre-warm failed (non-fatal)")

            threading.Thread(target=_warm, daemon=True).start()

        # dispatch-phase dwell: the jit launch plus the fallback-closure/
        # pre-warm setup riding the same half (everything past inputs)
        self.host_phase.record(
            "serial", "dispatch", (time.perf_counter() - t_dispatch0) * 1000.0
        )
        return _PendingTick(
            wire=wire,
            fallback=fallback,
            ts_ms=ts_ms,
            ts5=ts5,
            ts15=ts15,
            bucket15=bucket15,
            dispatched_at=time.perf_counter(),
            rows=self.registry.frozen_rows(),
            trace=trace,
            drive="serial",
            ingest_mono=ingest_mono,
            # double-buffered donation: this tick's post state re-enters
            # the slot rotation once the tick finalizes (tagged with the
            # reset generation so a post-reset finalize discards it)
            spare=(
                (self.state, self._state_generation)
                if mode == "double"
                else None
            ),
        )

    async def _finalize_tick(self, pending: _PendingTick) -> list:
        """Consume one dispatched tick's wire: refresh host policy state and
        emit its fired signals through the three sinks. Afterwards the
        tick's trace is completed — ring append, ``trace`` event, and the
        slow-tick flight-recorder check — even if finalize raised (an
        errored tick is exactly what the recorder must capture)."""
        trace = pending.trace
        with trace.activate():
            try:
                return await self._finalize_tick_inner(pending, trace)
            except BaseException as exc:
                # ANY exception escaping finalize — spanned or not — must
                # flag the trace, or the recorder would file the tick ok
                trace.mark_error(exc)
                raise
            finally:
                self.tracer.complete(trace, snapshot_fn=self._flight_snapshot)
                # double-buffered donation slot rotation: a finalized
                # tick's post state becomes the next dispatch's scratch —
                # UNLESS it is still the engine's current state (the tick
                # was finalized before any newer dispatch, e.g. a
                # flush_pending drain), which must never be donated while
                # also being the next launch's input, or it predates a
                # cold reset (stale generation: the buffers may belong to
                # the failed lineage the reset just discarded)
                if pending.spare is not None:
                    spare_state, spare_gen = pending.spare

                    def _pool(st):
                        if len(self._spare_slots) < self._spare_slots_max:
                            self._spare_slots.append(st)

                    # promote a previously parked state first: ANY later
                    # finalize's wire fetch proves the computation that
                    # read the parked buffers (the dispatch right after
                    # parking) has completed — without this, one parked
                    # state would stay pinned for the rest of a
                    # sustained-load run
                    d = self._deferred_spare
                    if (
                        d is not None
                        and d[1] == self._state_generation
                        and d[0] is not self.state
                    ):
                        _pool(d[0])
                        self._deferred_spare = None
                    if spare_gen == self._state_generation:
                        if spare_state is not self.state:
                            _pool(spare_state)
                        else:
                            # light load: this tick's post state is still
                            # the engine's current state — park it until
                            # a later dispatch replaces self.state
                            self._deferred_spare = (spare_state, spare_gen)

    async def _finalize_tick_inner(self, pending: _PendingTick, trace) -> list:
        ts5, ts15 = pending.ts5, pending.ts15
        drive = getattr(pending, "drive", "serial") or "serial"
        # ONE device fetch per tick: the packed wire (context scalars +
        # compacted fired entries). Everything host-side below reads it.
        t_fetch0 = time.perf_counter()
        with self.latency.stage("wire_fetch"), trace.span("wire_fetch") as sp_wire:
            pre_unpacked = getattr(pending, "unpacked", None)
            if pre_unpacked is not None:
                # chunk drives that batch-decoded the whole wire block in
                # one vectorized pass (unpack_wire_block) hand the tick's
                # (WireFired, ctx) here — its decode cost was already
                # attributed at flush
                unpacked = pre_unpacked
            else:
                unpacked = unpack_wire(
                    pending.wire, numeric_digest=self.numeric_digest,
                    ingest_digest=self.ingest_digest,
                )
        t_fetch_end = time.perf_counter()
        if drive == "serial":
            # the serial drive's one blocking device interaction; on the
            # batch drives the per-tick wire is an ALREADY-LANDED numpy
            # row — parsing it is decode work (the chunk's np.asarray
            # bracket captured the real device wait), so t_decode0 below
            # reaches back to cover this unpack
            self.host_phase.record(
                drive, "device_wait", (t_fetch_end - t_fetch0) * 1000.0
            )
        if self.freshness.enabled:
            # logical close→dispatch (this tick's clock vs the newest
            # evaluated bar's close — exact live, deterministic in replay)
            close_ms = max(ts5 + FIVE_MIN_S, ts15 + FIFTEEN_MIN_S) * 1000
            self.freshness.observe_stage(
                "close_to_dispatch", pending.ts_ms - close_ms
            )
            ingest_mono = getattr(pending, "ingest_mono", None)
            if ingest_mono is not None:
                self.freshness.observe_stage(
                    "ingest_to_dispatch",
                    max((pending.dispatched_at - ingest_mono) * 1000.0, 0.0),
                )
            if drive == "serial":
                # batch drives observe this once per chunk at flush (their
                # per-tick wire is an already-landed host array)
                self.freshness.observe_stage(
                    "dispatch_to_fetch",
                    (t_fetch_end - pending.dispatched_at) * 1000.0,
                )
        t_decode0 = t_fetch_end if drive == "serial" else t_fetch0
        fired_w, ctx_scalars = unpacked
        sp_wire.set(overflow=bool(fired_w.overflow))
        # resync pressure: beta/corr rows reading null until the next full
        # recompute (absent from older/fabricated wires → 0). This decode
        # runs for EVERY backend — serial, donated, scanned, and backtest
        # ticks all finalize here.
        BC_DIRTY_ROWS.set(int(ctx_scalars.get("bc_dirty_rows", 0) or 0))
        # numeric-health digest (same trailing block on every backend's
        # wire): gauges + anomaly force-emit (obs/numeric.py)
        if "numeric_digest" in ctx_scalars:
            with trace.span("numeric_digest") as sp_num:
                digest = self.numeric.observe(
                    ctx_scalars["numeric_digest"],
                    tick_ms=pending.ts_ms,
                    trace_id=trace.trace_id,
                    snapshot_fn=self._flight_snapshot,
                )
                sp_num.set(
                    nan_rows=digest["nan_total"], inf_rows=digest["inf_total"]
                )
        # ingest-health digest (trailing block on every backend's wire):
        # staleness/coverage gauges + the SLO burn/recovery state machine
        # (obs/ingest.py force-emits ingest_anomaly / ingest_recovered)
        if "ingest_digest" in ctx_scalars:
            with trace.span("ingest_digest") as sp_ing:
                idig = self.ingest_monitor.observe_digest(
                    ctx_scalars["ingest_digest"],
                    tick_ms=pending.ts_ms,
                    trace_id=trace.trace_id,
                    snapshot_fn=self._flight_snapshot,
                )
                sp_ing.set(
                    stale_rows=idig["stale_total"],
                    fresh5=idig["5m"]["fresh"],
                    fresh15=idig["15m"]["fresh"],
                )
        # The full TickOutputs exists only if a degenerate path needs it:
        # compaction overflow (>WIRE_MAX_FIRED fired pairs) or a wire
        # without the emission payload. Re-running the full step costs one
        # serial round trip — acceptable on a pathological tick, free
        # otherwise.
        outputs = None
        if fired_w.overflow or fired_w.payload is None:
            if fired_w.overflow:
                self.overflow_ticks += 1
                OVERFLOW_TICKS.inc()
            with self.latency.stage("overflow_fallback"), trace.span(
                "overflow_fallback", overflow=bool(fired_w.overflow)
            ):
                outputs = pending.fallback()
        regime = ctx_scalars["market_regime"]
        has_ctx = ctx_scalars["valid"]
        self.grid_only_policy = GridOnlyPolicy.resolve(
            regime if has_ctx else None, self.market_breadth
        )
        self.at_consumer.grid_only_policy = self.grid_only_policy

        # regime-transition digest (host-side notifier)
        digest = self.notifier.build_message(ctx_scalars)
        if digest:
            self.telegram_consumer.dispatch_signal(digest)

        # leverage calibration once per 15m bucket, needs a valid context;
        # inputs decoded from the wire (zero device fetches) when present
        if has_ctx:
            from binquant_tpu.io.leverage import CalibrationInputs

            if "calib_valid" in ctx_scalars:
                calib = CalibrationInputs(
                    valid=ctx_scalars["calib_valid"],
                    close=ctx_scalars["calib_close"],
                    atr_pct=ctx_scalars["calib_atr_pct"],
                    regime=regime,
                    stress=ctx_scalars["market_stress_score"],
                    confidence=1.0,
                )
                self._run_leverage_calibration(
                    pending.bucket15, calib, rows=pending.rows
                )
            else:
                # calib rows absent from the wire (fabricated test wires):
                # fall back to the full outputs' context (and keep the
                # fallback result so later consumers don't re-run the step)
                if outputs is None:
                    outputs = pending.fallback()
                self._run_leverage_calibration(
                    pending.bucket15, outputs.context, rows=pending.rows
                )

        # carry regime state across restarts (checkpoint introspection; the
        # quiet-hours override itself is applied device-side from the
        # CURRENT tick's context). An invalid context clears it.
        if has_ctx:
            self._last_regime = regime
            self._last_transition_strength = ctx_scalars[
                "market_regime_transition_strength"
            ]
        else:
            self._last_regime = None
            self._last_transition_strength = 0.0

        # emit fired signals through the three sinks
        t_emit0 = time.perf_counter()
        settings = self.at_consumer.autotrade_settings
        from binquant_tpu.engine.step import EMISSION_LAYOUTS

        with trace.span("extract_fired") as sp_extract:
            fired = extract_fired(
                outputs,
                # row→symbol AS OF dispatch: a row freed and re-claimed
                # between dispatch and finalize must not attribute this
                # tick's signal to the new occupant
                pending.rows,
                env=self.config.env,
                exchange=self.at_consumer.exchange,
                # use_enum_values schemas store the plain value string; raw
                # enums (tests, direct construction) need .value
                market_type=getattr(
                    settings.market_type, "value", settings.market_type
                ),
                settings=settings,
                enabled=self.enabled_strategies,
                # pre-materialization skip: standing triggers already
                # emitted for this bar cost nothing (no diagnostics fetch,
                # no payloads)
                skip=lambda strategy, row: self._already_emitted(
                    strategy, pending.rows.name_of(row), ts5, ts15
                ),
                unpacked=unpacked,
                # diagnostics slot layout recorded when this wire_enabled
                # combo was traced — lets emission decode the wire's
                # per-slot payload instead of fetching device arrays
                diag_layout=EMISSION_LAYOUTS.get(self._wire_enabled_key()),
            )
            sp_extract.set(fired=len(fired))
        with trace.span("dedupe") as sp_dedupe:
            fired = self._dedupe_fired(fired, ts5, ts15)
            sp_dedupe.set(kept=len(fired))
        if trace.active:
            # signal provenance: every outbound payload joins back to the
            # tick that produced it — stamped BEFORE any sink sees it
            for signal in fired:
                signal.trace_id = trace.trace_id
                signal.tick_seq = trace.tick_seq
                signal.value.metadata["trace_id"] = trace.trace_id
                signal.value.metadata["tick_seq"] = trace.tick_seq
                signal.analytics["trace_id"] = trace.trace_id
                signal.analytics["tick_seq"] = trace.tick_seq
                signal.message += (
                    f"\n- Trace: {trace.trace_id}/{trace.tick_seq}"
                )
        # subscription fan-out (ISSUE 14): join the deduped, provenance-
        # stamped fired set against the compiled subscription planes in
        # ONE extra kernel dispatch and mint broadcast frames. Runs at the
        # shared finalize, so every backend (serial/donated/scanned/
        # backtest) produces the identical recipient sets. When the
        # delivery plane is on the hub handoff happens on its fanout
        # worker (signal.fanout_frame, enqueued below); otherwise the
        # plane offers to connections directly (bounded, non-blocking).
        if self.fanout is not None and fired:
            with trace.span("fanout_match") as sp_fanout:
                fanout_stats = self.fanout.on_fired(
                    fired, ctx_scalars, tick_ms=pending.ts_ms
                )
                sp_fanout.set(**fanout_stats)
        # decode half done (wire → deduped, provenance-stamped signals);
        # the emit half below is sink dispatch only
        t_emit_phase0 = time.perf_counter()
        self.host_phase.record(
            drive, "decode", (t_emit_phase0 - t_decode0) * 1000.0
        )

        def _sig_lag_ms(signal) -> int:
            return pending.ts_ms - self._bar_close_ms(
                signal.strategy, ts5, ts15
            )

        with trace.span("emission", signals=len(fired)):
            for signal in fired:
                # per-signal freshness, stamped BEFORE the analytics POST
                # so the payload itself carries its staleness (additive
                # field, absent while BQT_FRESHNESS=0 — satellite: no
                # Prometheus scrape needed downstream)
                sink_acks: dict[str, float] | None = None
                lag0: float | None = None
                if self.freshness.enabled:
                    lag0 = _sig_lag_ms(signal)
                    signal.freshness_ms = round(
                        lag0
                        + (time.perf_counter() - pending.dispatched_at)
                        * 1000.0,
                        3,
                    )
                    signal.analytics["freshness_ms"] = signal.freshness_ms
                    signal.value.metadata["freshness_ms"] = signal.freshness_ms
                    sink_acks = {}

                    def _ack(sink: str, lag0=lag0, acks=sink_acks) -> None:
                        acks[sink] = lag0 + (
                            time.perf_counter() - pending.dispatched_at
                        ) * 1000.0
                else:
                    def _ack(sink: str) -> None:
                        pass
                if self.delivery is not None:
                    # delivery plane (ISSUE 13): finalize ENQUEUES and
                    # returns — a WAL append per at-least-once sink plus
                    # bounded-queue puts; the sink round trips, retries,
                    # and breaker waits all happen on the plane's workers,
                    # so the tick thread's emit dwell stays bounded no
                    # matter how the sinks behave. bqt_sink_delivery_ms
                    # is observed by the worker at ACK (close→acked-
                    # through-the-queue); the SLO check here judges
                    # close→emit (the plane accepted the signal).
                    with trace.span(
                        "delivery.enqueue",
                        strategy=signal.strategy,
                        symbol=signal.symbol,
                    ):
                        self.delivery.enqueue_fired(
                            signal,
                            tick_ms=pending.ts_ms,
                            lag0_ms=lag0,
                            dispatched_at=pending.dispatched_at,
                        )
                    if self.freshness.enabled:
                        self.freshness.observe_signal(
                            strategy=signal.strategy,
                            symbol=signal.symbol,
                            close_to_emit_ms=signal.freshness_ms,
                            sink_ack_ms=None,
                            tick_ms=pending.ts_ms,
                            trace_id=signal.trace_id,
                            phases=(
                                self.host_phase.open_split(drive)
                                or self.host_phase.last_chunk
                            ),
                            snapshot_fn=self._flight_snapshot,
                        )
                    continue
                with trace.span(
                    "sink.analytics",
                    strategy=signal.strategy,
                    symbol=signal.symbol,
                ):
                    dispatch_signal_record(self.binbot_api, signal.analytics)
                _ack("analytics")
                with trace.span(
                    "sink.telegram",
                    strategy=signal.strategy,
                    symbol=signal.symbol,
                ):
                    self.telegram_consumer.dispatch_signal(signal.message)
                _ack("telegram")
                try:
                    with trace.span(
                        "sink.autotrade",
                        strategy=signal.strategy,
                        symbol=signal.symbol,
                    ):
                        await self.at_consumer.process_autotrade_restrictions(
                            signal.value
                        )
                    # ack only on success: a swallowed sink failure must
                    # not record a delivery latency for a sink that never
                    # delivered (the error is visible in the span status
                    # and bqt_sink_emissions_total)
                    _ack("autotrade")
                except Exception:
                    logging.exception(
                        "autotrade processing crashed for %s/%s; continuing",
                        signal.strategy,
                        signal.symbol,
                    )
                if self.freshness.enabled:
                    # close→sink-ack + per-sink delivery + the SLO check
                    # (breach force-emits with the chunk's phase split)
                    self.freshness.observe_signal(
                        strategy=signal.strategy,
                        symbol=signal.symbol,
                        close_to_emit_ms=signal.freshness_ms,
                        sink_ack_ms=sink_acks,
                        tick_ms=pending.ts_ms,
                        trace_id=signal.trace_id,
                        # the PRODUCING chunk's split-so-far (its
                        # occupancy closes after this finalize); fall
                        # back to the last closed chunk outside one
                        phases=(
                            self.host_phase.open_split(drive)
                            or self.host_phase.last_chunk
                        ),
                        snapshot_fn=self._flight_snapshot,
                    )
        self.latency.record("emission", (time.perf_counter() - t_emit0) * 1000.0)
        self.signals_emitted += len(fired)
        # Signal-latency accounting (the number a trading system cares
        # about, not just per-tick wall time): dispatch→emit is the
        # pipelining lag this tick actually paid; candle→emit adds how
        # stale the evaluated bar already was when the tick dispatched
        # (logical, from the tick's own clock — exact live, where tick
        # time ≈ wall clock).
        emit_lag_ms = (time.perf_counter() - pending.dispatched_at) * 1000.0
        self.latency.record("dispatch_to_emit", emit_lag_ms)
        for signal in fired:
            # which tick produced this signal — pipelined emission happens
            # one call later, so callers (replay A/B) must not attribute it
            # to the tick that evicted it
            signal.tick_ms = pending.ts_ms
            SIGNALS.labels(strategy=signal.strategy).inc()
            # freshness_ms rides the signal event only when stamped (the
            # no-observatory record stays byte-identical)
            extra = (
                {"freshness_ms": signal.freshness_ms}
                if signal.freshness_ms is not None
                else {}
            )
            get_event_log().emit(
                "signal",
                strategy=signal.strategy,
                symbol=signal.symbol,
                direction=str(signal.value.direction),
                autotrade=bool(signal.value.autotrade),
                tick_ms=pending.ts_ms,
                trace_id=signal.trace_id,
                tick_seq=signal.tick_seq,
                **extra,
            )
            self.latency.record(
                "candle_to_emit", _sig_lag_ms(signal) + emit_lag_ms
            )
        # signal-outcome observatory (ISSUE 12): the emitted (post-dedupe)
        # set enters the open registry anchored on this tick's evaluated
        # 5m bar, then everything due matures against the live ring in ONE
        # jit'd gather. The gather is timestamp-bounded, so reading the
        # engine's CURRENT state — post-chunk on the batch drives, a tick
        # ahead on a pipelined live loop — yields the identical matured
        # set every drive pins (obs/outcomes.py module docstring).
        if self.outcomes.enabled:
            for signal in fired:
                self.outcomes.register(
                    strategy=signal.strategy,
                    symbol=signal.symbol,
                    row=signal.row,
                    entry_ts5=ts5,
                    direction=signal.value.direction,
                    trace_id=signal.trace_id,
                    tick_seq=signal.tick_seq,
                    tick_ms=pending.ts_ms,
                )
            self.outcomes.on_tick(ts5, self.state.buf5)
        self.host_phase.record(
            drive, "emit", (time.perf_counter() - t_emit_phase0) * 1000.0
        )
        return fired

    def _donation_mode(self) -> str | None:
        """How THIS dispatch donates the engine state (BQT_DONATE).

        Donation COMPOSES with the symbol mesh (the ISSUE 19 decision):
        GSPMD compiles one executable spanning every shard, so donating a
        sharded input aliases each per-device buffer with the matching
        output shard — the rotation logic below is unchanged, it just
        rotates sharded states. Two mesh-specific obligations: spare
        slots must be CREATED sharded (a fresh unsharded scratch would
        change the jit signature and silently recompile the db step per
        dispatch), and the generation stamp is scoped to the state
        lineage *including its placement* — ``_invalidate_spares`` bumps
        it on cold resets AND on checkpoint restores (which may install a
        state saved at a different shard count), so no spare from a
        pre-restore lineage can ever be donated into the new one.
        Per-shard spare rotation/generations collapse to this single
        rotation because one process drives one executable over all
        shards; a per-process pod runtime would instantiate one rotation
        per process, which is this exact code.

        * ``None`` — copying step (donation off).
        * ``"single"`` — ``pipeline_depth <= 1``: the classic ISSUE-4
          scheme donating the input state itself. Safe because
          process_tick finalizes tick i before dispatching i+1, so the
          donated fallback's lazy ``self.state`` read at finalize still
          sees tick i's post state.
        * ``"double"`` — ``pipeline_depth >= 2`` (ISSUE 9): the
          double-buffered step (``tick_step_wire_db``) donates a SECOND
          resident slot — rotated through the ``self._spare_slots`` free
          pool (plus the light-load ``self._deferred_spare`` parking
          slot) — while the input state stays live, so every in-flight
          tick's fallback keeps its own (eagerly captured) post state.
          Host finalize of tick i overlaps the device dispatch of tick
          i+1 with donated buffers live — the depth-2 pipelining
          donation previously forfeited.

        The crash ring's semantics under ``single`` donation: a launch
        that fails after consuming its buffers cannot carry on with the
        pre-tick state — _recover_after_donated_failure resets cold
        (logged loudly, counted) instead of crash-looping on deleted
        arrays. Under ``double`` only the spare slot is consumed; the
        input state survives a failed launch intact, so no reset is
        needed (the slot is simply re-allocated next dispatch). Host-side
        errors before the launch leave state intact either way.
        """
        if not self._donate_cfg:
            return None
        return "single" if self.pipeline_depth <= 1 else "double"

    def _use_donated_step(self) -> bool:
        """Back-compat boolean view of :meth:`_donation_mode`."""
        return self._donation_mode() is not None

    def _fresh_state(self):
        """A cold empty EngineState carrying the engine's placement —
        sharded over the symbol mesh when one is active, so spares,
        scratch slots, and warm-up states always match the live state's
        jit signature."""
        state = initial_engine_state(self.capacity, window=self.window)
        if self.mesh is not None:
            from binquant_tpu.parallel.mesh import shard_engine_state

            state = shard_engine_state(state, self.mesh)
        return state

    def _invalidate_spares(self, why: str) -> None:
        """Retire every donation spare of the current state lineage —
        cold resets AND checkpoint restores route through here, so a
        state installed from a different lineage (possibly saved at a
        different shard count and re-sliced) can never receive a donated
        spare that aliases the old lineage's buffers."""
        self._spare_slots.clear()
        self._deferred_spare = None
        self._state_generation += 1
        logging.info(
            "donation spares invalidated (%s); state generation now %d",
            why,
            self._state_generation,
        )

    def _reset_state_cold(self, why: str) -> None:
        """Replace an unrecoverable engine state with a cold empty one —
        the engine recovers like a restart without a checkpoint
        (strategy-blind until buffers refill). Logged loudly, counted.
        The replacement carries the mesh sharding when one is active — an
        unsharded replacement would silently repin the whole ~66
        MB-per-copy state on one chip (and force a fresh
        sharding-signature recompile) for the rest of the process."""
        self.donated_state_resets += 1
        logging.error(
            "%s; resetting engine state cold (reset #%d — buffers must "
            "refill before strategies re-arm)",
            why,
            self.donated_state_resets,
        )
        self.state = self._fresh_state()
        # drop the double-buffer slots too — they may alias buffers the
        # failed computation produced — and invalidate any spare still
        # riding a pending tick of the failed lineage
        self._invalidate_spares(f"cold reset: {why}")
        for latest in self._host_latest.values():
            latest[:] = -1
        self._carry_desync_reason = "cold_start"

    def _recover_after_donated_failure(self, prev_state) -> None:
        """A donated launch raised SYNCHRONOUSLY: if the pre-tick buffers
        were actually consumed (deleted), reset cold rather than
        crash-looping on deleted arrays."""
        import jax

        deleted = any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree_util.tree_leaves(prev_state)
        )
        if not deleted:
            return  # launch failed before donation; pre-tick state intact
        self._reset_state_cold(
            "donated tick failed after consuming its buffers"
        )

    def recover_if_state_poisoned(self) -> None:
        """Crash-ring follow-up for ASYNC device faults. Dispatch is
        asynchronous, so a device-side failure in a launched tick is NOT
        raised by the launch — it surfaces at the first use of its
        outputs (the wire fetch at finalize). By then ``self.state``
        already holds the failed computation's outputs, and under
        donation the pre-tick buffers are gone too, so every subsequent
        fold/dispatch would crash-loop on an unusable state with no
        reset ever firing. Called by the tick loop after it swallows a
        processing error: probe the state (deleted-buffer flags plus one
        small-leaf materialization, which re-raises the device error iff
        the producing computation failed) and reset cold when unusable.
        Healthy states pass the probe for a few microseconds; intact
        failures (host-side errors before a launch) are left alone."""
        import jax

        try:
            poisoned = any(
                getattr(leaf, "is_deleted", lambda: False)()
                for leaf in jax.tree_util.tree_leaves(self.state)
            )
            if not poisoned:
                np.asarray(self.state.mrf_last_emitted)  # (S,) probe
                return
        except Exception:
            pass
        self._reset_state_cold(
            "engine state poisoned by a failed device computation"
        )

    def _dev_scalar(self, name: str, value):
        """Device scalar cached per input name, re-uploaded only when the
        value changes (NaN-stable: NaN == previous NaN counts as a hit)."""
        import jax.numpy as jnp

        hit = self._scalar_cache.get(name)
        if hit is not None and (
            hit[0] == value or (value != value and hit[0] != hit[0])
        ):
            return hit[1]
        arr = jnp.asarray(value)
        self._scalar_cache[name] = (value, arr)
        return arr

    def _place_symbol_array(self, arr):
        """Host (S,) array → device, split over the symbol mesh when one is
        active (pre-placing avoids a per-tick resharding inside jit).

        Under a mesh this is the shard-local ingest boundary: the host
        array is sliced per shard and each slice ships straight to the
        device that owns those rows (``assemble_sharded`` →
        ``make_array_from_single_device_arrays``) — no full-array
        ``device_put`` on the hot path, and the identical construction a
        multi-host pod performs per process."""
        if self.mesh is None:
            import jax.numpy as jnp

            return jnp.asarray(arr)
        from binquant_tpu.parallel.mesh import assemble_sharded

        return assemble_sharded(self.mesh, np.asarray(arr))

    def _tracked_mask(self):
        """Device-resident occupied-rows mask, rebuilt only on registry
        membership changes. During a serial re-drive the plan-time
        snapshot wins over the live registry (see _redrive_serial)."""
        if self._tracked_override is not None:
            return self._place_symbol_array(
                np.asarray(self._tracked_override)
            )
        cached = self._tracked_cache
        if cached is not None and cached[0] == self.registry.version:
            return cached[1]
        arr = self._place_symbol_array(self.registry.active_rows)
        self._tracked_cache = (self.registry.version, arr)
        return arr

    def _ledger_sig(self, u5, u15, incremental: bool) -> str:
        """Human-readable arg-shape signature for the executable ledger —
        the same axes the jit cache keys on (buffer shape, padded update
        buckets, path flags), compact enough for a metric-adjacent JSON."""
        return (
            f"S{self.capacity}xW{self.window}"
            f" u5[{int(np.asarray(u5[0]).shape[-1])}]"
            f" u15[{int(np.asarray(u15[0]).shape[-1])}]"
            f" incr={int(bool(incremental))}"
            f" digest={int(self.numeric_digest)}"
            + (" ingest=1" if self.ingest_digest else "")
        )

    def _wire_enabled_key(self) -> tuple[str, ...]:
        """The static wire_enabled tuple this engine compiles with — also
        the key into ``EMISSION_LAYOUTS`` for payload decoding."""
        return tuple(
            sorted(
                LIVE_STRATEGIES
                if self.enabled_strategies is None
                else self.enabled_strategies
            )
        )

    def _bar_close_ms(self, strategy: str, ts5: int, ts15: int) -> int:
        """Close time (ms) of the bar a strategy evaluated this tick — the
        freshness anchor every close→* stamp measures against."""
        bar_ts = (
            ts5 + FIVE_MIN_S
            if strategy in FIVE_MIN_STRATEGIES
            else ts15 + FIFTEEN_MIN_S
        )
        return bar_ts * 1000

    def _already_emitted(
        self, strategy: str, symbol: str | None, ts5: int, ts15: int
    ) -> bool:
        """Check (without marking) whether this (strategy, symbol) already
        emitted for the bar being evaluated. Keyed by symbol name — registry
        rows are recycled, so a row-keyed entry could suppress a NEW
        symbol's first signal. The caller resolves the symbol through the
        tick's dispatch-time row snapshot."""
        if symbol is None:
            return True  # untracked row: nothing to emit
        bar_ts = ts5 if strategy in FIVE_MIN_STRATEGIES else ts15
        return self._last_emitted.get((strategy, symbol)) == bar_ts

    def _dedupe_fired(self, fired: list, ts5: int, ts15: int) -> list:
        """Once-per-bar emission dedupe (mark + filter). consume_loop
        re-ticks every second within a bucket; a standing trigger must emit
        at most once per bar (the reference dispatches once per candle
        arrival)."""
        kept = []
        for signal in fired:
            bar_ts = ts5 if signal.strategy in FIVE_MIN_STRATEGIES else ts15
            key = (signal.strategy, signal.symbol)
            if self._last_emitted.get(key) == bar_ts:
                continue
            self._last_emitted[key] = bar_ts
            kept.append(signal)
        return kept

    def prune_symbols(self, keep: list[str]) -> int:
        """Drop registry rows for symbols outside ``keep`` and clear their
        buffer rows. Called after a checkpoint restore: universe churn
        would otherwise leak rows across restarts until ``registry.add``
        exhausts capacity and the boot crash-loops on the stale snapshot."""
        import jax.numpy as jnp

        from binquant_tpu.engine.buffer import reset_rows

        keep_rows = {
            r for r in (self.registry.row_of(s) for s in keep) if r is not None
        }
        stale = [
            (sym, row)
            for sym, row in self.registry.to_mapping().items()
            if row not in keep_rows
        ]
        if not stale:
            return 0
        for sym, _ in stale:
            self.registry.remove(sym)
        rows_np = np.array([row for _, row in stale], np.int32)
        rows = jnp.asarray(rows_np)
        self.state = self.state._replace(
            buf5=reset_rows(self.state.buf5, rows),
            buf15=reset_rows(self.state.buf15, rows),
        )
        # cleared rows can be reclaimed by NEW symbols whose first append
        # the stale per-row carry would misread — force one full recompute
        # (which re-inits every row's carry) before going incremental again
        for latest in self._host_latest.values():
            latest[rows_np] = -1
        self._mark_carry_desynced("churn")
        logging.info("pruned %d symbols that left the universe", len(stale))
        return len(stale)

    # -- checkpoint/resume ------------------------------------------------------

    def host_carries(self) -> dict:
        """JSON-serializable host-side state that must survive a restart so
        the first post-restore tick behaves identically: regime carry for
        the quiet-hours override, per-bar emission dedupe, bucket-job
        watermarks, and the notifier's transition dedupe. (The device-side
        RegimeCarry incl. ``regime_stable_since`` rides in EngineState.)"""
        return {
            "saved_at_s": time.time(),
            "ticks_processed": self.ticks_processed,
            "signals_emitted": self.signals_emitted,
            "last_regime": self._last_regime,
            "last_transition_strength": self._last_transition_strength,
            # NOTE: the breadth/calibration bucket watermarks are NOT
            # carried — they guard host data (market_breadth) that does not
            # survive a restart; restoring them would suppress the refetch
            # for up to a full bucket and leave breadth-gated logic blind.
            "last_emitted": [
                [strategy, symbol, ts]
                for (strategy, symbol), ts in self._last_emitted.items()
            ],
            "notifier_last_transition": self.notifier.last_transition_sent,
            # open-signal outcome registry (ISSUE 12): signals emitted but
            # not yet matured at every horizon — a restart mid-horizon
            # must mature the same signal_outcome set an uninterrupted
            # run would (tests/test_outcomes.py pins the round trip)
            "outcomes_open": self.outcomes.snapshot_open(),
        }

    def note_state_restored(self, migrated: bool = False) -> None:
        """Post-checkpoint-restore hook: rebuild the host-side latest-ts
        mirror from the restored device buffers (one D2H at boot) and set
        the carry sync state. A v2 restore carries the indicator state in
        the EngineState pytree (synced); a migrated v1 restore has only the
        empty template carry — the first tick runs the full recompute."""
        from binquant_tpu.engine.buffer import ring_latest_times

        carry_synced = not migrated
        for key, buf in (("5m", self.state.buf5), ("15m", self.state.buf15)):
            # restored archives are canonical (cursor 0), but read through
            # the ring-aware helper so a mid-phase state is also correct
            latest = np.asarray(ring_latest_times(buf)).astype(np.int64)
            self._host_latest[key] = latest
            # a v2 archive written by a classic-path deployment
            # (BQT_INCREMENTAL=0 skips carry maintenance) holds a stale/
            # empty carry: trust it only if it matches the restored windows
            carry_ts = np.asarray(
                getattr(
                    self.state.indicator_carry,
                    "pack5" if key == "5m" else "pack15",
                ).last_ts
            ).astype(np.int64)
            if not np.array_equal(carry_ts, latest):
                carry_synced = False
        self._carry_desync_reason = None if carry_synced else "cold_start"

    def restore_host_carries(self, carries: dict) -> None:
        self.ticks_processed = int(carries.get("ticks_processed", 0))
        self.signals_emitted = int(carries.get("signals_emitted", 0))
        regime = carries.get("last_regime")
        self._last_regime = None if regime is None else int(regime)
        self._last_transition_strength = float(
            carries.get("last_transition_strength", 0.0)
        )
        self._last_emitted = {
            (strategy, symbol): int(ts)
            for strategy, symbol, ts in carries.get("last_emitted", [])
        }
        notifier_last = carries.get("notifier_last_transition")
        self.notifier.last_transition_sent = (
            None if notifier_last is None else int(notifier_last)
        )
        self.outcomes.restore_open(carries.get("outcomes_open"))

    _HB_WARN_EVERY_S = 60.0

    def touch_heartbeat(self) -> None:
        """Liveness file checked by healthcheck.py (main.py:30-32).

        Write failures are counted (``bqt_heartbeat_write_failures_total``;
        /healthz reports degraded liveness while they persist) and the
        warning is rate-limited — a full disk at a 1 s tick cadence must
        not turn the log into a firehose that buries real errors.

        Also the boot compile_summary's polling point (every backend's
        tick loop passes through here): emitted at the first heartbeat
        where no ledger watch is in flight, so the fallback pre-warm's
        background compile — which routinely outlives the first tick —
        makes it into the once-per-boot totals.
        """
        # >= 2 ticks: the incremental engine's SECOND tick compiles the
        # fast-path wire variant (tick 1 is always the cold-start full
        # recompute) — a summary cut at tick 1 would miss it
        if self.ticks_processed > 1 and not LEDGER.summary_emitted:
            LEDGER.emit_summary_when_quiet(reason="boot")
        try:
            self.heartbeat_path.write_text(str(time.time()))
            self._last_heartbeat_s = time.time()
            self._hb_consecutive_failures = 0
        except OSError:
            self.heartbeat_write_failures += 1
            self._hb_consecutive_failures += 1
            HEARTBEAT_FAILURES.inc()
            now = time.monotonic()
            if now - self._hb_last_warn >= self._HB_WARN_EVERY_S:
                self._hb_last_warn = now
                logging.warning(
                    "failed to write heartbeat file (%d consecutive, "
                    "%d total; further warnings rate-limited to one per "
                    "%.0fs)",
                    self._hb_consecutive_failures,
                    self.heartbeat_write_failures,
                    self._HB_WARN_EVERY_S,
                )

    def _flight_snapshot(self) -> dict:
        """Engine state attached to a flight-recorder (slow/errored tick)
        force-emit: what the engine looked like when the breach happened.
        Attribute reads only — computed lazily, never on healthy ticks."""
        return {
            "queue_depth": {
                "batcher5": len(self.batcher5),
                "batcher15": len(self.batcher15),
            },
            "symbols": len(self.registry.names),
            "pending_ticks": len(self._pending),
            "ticks_processed": self.ticks_processed,
            "signals_emitted": self.signals_emitted,
            "overflow_ticks": self.overflow_ticks,
            "incremental_ticks": self.incremental_ticks,
            "full_recompute_ticks": self.full_recompute_ticks,
            "scanned_ticks": self.scanned_ticks,
            "backtest_ticks": self.backtest_ticks,
            "carry_desync_reason": self._carry_desync_reason,
            "numeric_anomaly_ticks": self.numeric.anomaly_ticks,
            "drift_alarms": self.drift.alarms,
            # ingest-health observatory: staleness-burn state at the breach
            "ingest_anomaly_ticks": self.ingest_monitor.anomaly_ticks,
            "ingest_burning": self.ingest_monitor.burning,
            # latency observatory: the newest chunk's occupancy split and
            # the freshness-SLO tally (attribute reads only)
            "freshness_slo_breaches": self.freshness.breaches,
            "host_phase_last_chunk": self.host_phase.last_chunk,
            # signal-outcome observatory: registry pressure at the breach
            "outcomes_open": len(self.outcomes._open),
            "outcome_evictions": self.outcomes.evictions,
            # delivery plane: per-sink queue depth + breaker state at the
            # breach (attribute reads only; None while the plane is off)
            "delivery": (
                {
                    name: {
                        "queue": lane.queue.qsize(),
                        "breaker": lane.breaker.state,
                        "deferred": lane.deferred,
                    }
                    for name, lane in self.delivery._lanes.items()
                }
                if self.delivery is not None
                else None
            ),
            # fan-out plane pressure at the breach (attribute reads only)
            "fanout": (
                {
                    "users": len(self.fanout.subscriptions),
                    "published": self.fanout.published,
                    "connections": self.fanout.hub.connections,
                    "shed": self.fanout.hub.shed,
                }
                if self.fanout is not None
                else None
            ),
        }

    def _mesh_snapshot(self) -> dict:
        """Sharded-plane section for /healthz: geometry + per-shard live
        row counts (host-side reads only — the registry mask, never a
        device fetch)."""
        if self.mesh is None:
            return {"enabled": False}
        from binquant_tpu.parallel.mesh import shard_bounds

        n = int(self.mesh.devices.size)
        bounds = shard_bounds(self.registry.capacity, n)
        active = self.registry.active_rows
        return {
            "enabled": True,
            "devices": n,
            "shards": [
                {
                    "shard": k,
                    "rows": [lo, hi],
                    "tracked_rows": int(active[lo:hi].sum()),
                }
                for k, (lo, hi) in enumerate(bounds)
            ],
            "state_generation": self._state_generation,
            "outbox_shards": (
                getattr(self.fanout, "outbox_shards", None)
                if self.fanout is not None
                else None
            ),
        }

    def health_snapshot(self, max_age_s: float = 1500.0) -> dict:
        """Liveness JSON for the /healthz endpoint (obs.exposition).

        ``status`` semantics: ``ok`` — a heartbeat write succeeded within
        ``max_age_s``; ``degraded`` — the engine is ticking but heartbeat
        writes are currently failing (file liveness is lying about us);
        ``stale`` — no successful heartbeat inside the window. Attribute
        reads only, safe to call inline on the event loop.
        """
        now = time.time()
        heartbeat_age = (
            None if self._last_heartbeat_s is None
            else round(now - self._last_heartbeat_s, 3)
        )
        last_tick_age = (
            None if self._last_tick_wall_s is None
            else round(now - self._last_tick_wall_s, 3)
        )
        if heartbeat_age is not None and heartbeat_age <= max_age_s:
            status = "degraded" if self._hb_consecutive_failures else "ok"
        else:
            status = "stale"
        # websocket ingest health: reconnects in the rolling window plus
        # the clients currently sitting in backoff. A reconnect STORM is
        # alive-but-impaired — the probe degrades (stays HTTP 200 per the
        # PR 1 contract; only stale is 503) so orchestrators see the
        # outage without restart-looping an engine that would only rejoin
        # the thundering herd.
        from binquant_tpu.io.websocket import WS_HEALTH

        ws = (self.ws_health or WS_HEALTH).snapshot()
        if status == "ok" and ws["storming"]:
            status = "degraded"
        # ingest staleness burning past BQT_INGEST_STALE_BUDGET is
        # alive-but-impaired, same contract as a ws storm: the payload
        # (and the ingest section below) says why, the probe stays 200
        ingest = self.ingest_monitor.snapshot()
        if status == "ok" and ingest["status"] == "degraded":
            status = "degraded"
        return {
            "status": status,
            "ws": ws,
            "heartbeat_age_s": heartbeat_age,
            "heartbeat_max_age_s": max_age_s,
            "heartbeat_write_failures": self.heartbeat_write_failures,
            "last_tick_age_s": last_tick_age,
            "ticks_processed": self.ticks_processed,
            "signals_emitted": self.signals_emitted,
            "overflow_ticks": self.overflow_ticks,
            "pending_ticks": len(self._pending),
            # incremental indicator path health: how often the fast path
            # actually ran vs fell back to the full-window recompute
            "incremental_enabled": self.incremental,
            "incremental_ticks": self.incremental_ticks,
            "full_recompute_ticks": self.full_recompute_ticks,
            # donated live buffers: ticks dispatched through the donated
            # executable, and cold resets after a post-donation failure
            # (zero in a healthy deployment)
            "donated_ticks": self.donated_ticks,
            "donated_state_resets": self.donated_state_resets,
            # scanned replay chunks: ticks evaluated inside fused lax.scan
            # dispatches (multi-tick lanes only; 0 in a live deployment)
            "scanned_ticks": self.scanned_ticks,
            "scan_chunks": self.scan_chunks,
            "scan_overflow_reruns": self.scan_overflow_reruns,
            # time-batched backtest chunks (multi-tick lanes only)
            "backtest_ticks": self.backtest_ticks,
            "backtest_chunks": self.backtest_chunks,
            "backtest_overflow_reruns": self.backtest_overflow_reruns,
            # numeric-health observatory (ISSUE 7): the last decoded wire
            # digest, anomaly/alarm tallies, and the last audit tick's
            # per-family carried-vs-fresh drift
            "numeric": {
                "digest_enabled": self.numeric_digest,
                "nan_budget": self.numeric.nan_budget,
                "anomaly_ticks": self.numeric.anomaly_ticks,
                "last_digest": self.numeric.last,
                "drift_meter": self.drift_meter_enabled,
                "drift_tol": self.drift.tol,
                "drift_audits": self.drift.audits,
                "drift_alarms": self.drift.alarms,
                "drift_audits_unmeasured": self.drift.skipped,
                "last_drift": self.drift.last,
            },
            # ingest-health observatory (ISSUE 15): the last decoded
            # ingest digest, SLO burn state, per-exchange feed lag and the
            # host monitor's churn/arrival tallies; per-symbol detail is
            # the paginated GET /debug/symbols route
            "ingest": ingest,
            # sharded execution plane (ISSUE 19): mesh geometry + which
            # contiguous row block each shard owns and how many of those
            # rows are live — the per-shard operating surface PR 15's
            # observatory was built to report through
            "mesh": self._mesh_snapshot(),
            # event-log drops (write failures / emit-after-close) — zero
            # in a healthy deployment
            "eventlog_dropped": get_event_log().dropped,
            # the latest completed tick's trace summary (total ms, slowest
            # stage, carry path) — None while tracing is sampled off
            "last_tick_trace": self.tracer.last_tick_trace(),
            # latency observatory (ISSUE 11): freshness stamps/SLO tally +
            # per-drive host-phase dwell and chunk occupancy
            "latency": {
                "freshness": self.freshness.snapshot(),
                "host_phase": self.host_phase.snapshot(),
            },
            # signal-outcome observatory (ISSUE 12): the per-strategy
            # hit-rate/excursion scoreboard + open-registry pressure
            "outcomes": self.outcomes.scoreboard(),
            # durable delivery plane (ISSUE 13): per-sink outbox queues,
            # breaker states, shed/ack counters, and WAL occupancy. A
            # plane under pressure (open breakers, WAL backlog) reads
            # DEGRADED here but keeps the probe at HTTP 200 — the PR-1
            # contract: only a stale heartbeat is worth a restart loop.
            "delivery": (
                self.delivery.snapshot()
                if self.delivery is not None
                else {"enabled": False}
            ),
            # subscription fan-out plane (ISSUE 14): compiled-population
            # size, match/publish counters, recompile kinds, and the hub's
            # per-connection scoreboard (attribute reads only)
            "fanout": (
                self.fanout.snapshot()
                if self.fanout is not None
                else {"enabled": False}
            ),
            # unified SLO verdict plane (ISSUE 16): every registered
            # SLO's burn state + invariant probes folded to one ok —
            # the full payload is GET /debug/slo
            "slo": (
                self.slo.verdict()
                if self.slo is not None
                else {"enabled": False, "ok": None}
            ),
        }

    # -- loops (main.py:37-57) ------------------------------------------------

    async def consume_loop(
        self, queue: asyncio.Queue, tick_interval_s: float = 1.0
    ) -> None:
        """Drain the ingest queue continuously; evaluate once per interval.

        Per-message crash isolation mirrors main.py:48-57: one bad payload
        is logged and skipped, the loop never dies. On shutdown
        (cancellation) any in-flight dispatched tick is flushed
        best-effort so its signals aren't dropped between the SIGTERM and
        the restart.
        """
        # start the delivery plane UP FRONT: unacked WAL entries from the
        # previous process replay at boot, not at the first new signal
        if self.delivery is not None:
            self.delivery.start()
        try:
            await self._consume_loop_body(queue, tick_interval_s)
        finally:
            if self._pending:
                try:
                    await self.flush_pending()
                except asyncio.CancelledError:
                    # already-cancelled task: a suspension point inside the
                    # flush re-raises; the sync parts (wire decode, sink
                    # enqueues) have still run — log and let the original
                    # cancellation proceed
                    logging.warning("shutdown flush interrupted mid-emission")
                except Exception:
                    logging.exception("shutdown flush failed")
            # retire the delivery plane last: best-effort drain of the
            # outbox queues, then stop the workers. Anything a down sink
            # never acked stays in the WAL and replays at the next boot —
            # the at-least-once contract across the SIGTERM.
            try:
                await self.aclose_delivery(drain_s=2.0)
            except asyncio.CancelledError:
                logging.warning("shutdown delivery drain interrupted")
            except Exception:
                logging.exception("shutdown delivery close failed")
            # the fan-out hub retires after the delivery drain (its lane's
            # last in-flight frames should reach connections first)
            try:
                await self.aclose_fanout()
            except asyncio.CancelledError:
                logging.warning("shutdown fanout close interrupted")
            except Exception:
                logging.exception("shutdown fanout close failed")

    async def _consume_loop_body(
        self, queue: asyncio.Queue, tick_interval_s: float
    ) -> None:
        last_tick = 0.0
        while True:
            try:
                timeout = max(tick_interval_s - (time.monotonic() - last_tick), 0.01)
                try:
                    kline = await asyncio.wait_for(queue.get(), timeout=timeout)
                    self.ingest(kline)
                    # drain whatever else is queued without blocking
                    while True:
                        try:
                            self.ingest(queue.get_nowait())
                        except asyncio.QueueEmpty:
                            break
                # py3.10: asyncio.TimeoutError is NOT the builtin; catching
                # only the builtin would route every idle-queue timeout to
                # the outer crash ring and starve the tick-dispatch block
                except (TimeoutError, asyncio.TimeoutError):
                    pass
                QUEUE_DEPTH.labels(queue="ingest").set(queue.qsize())
                if time.monotonic() - last_tick >= tick_interval_s:
                    if len(self.batcher5) or len(self.batcher15):
                        last_tick = time.monotonic()
                        await self.process_tick()
                        if self.early_emit and self._pending:
                            # emit this tick's signals as soon as its wire
                            # lands (~RTT) instead of next tick (~cadence)
                            await self.emit_ready()
                        if (
                            self.checkpoint is not None
                            and self.checkpoint.should_save(self)
                        ):
                            # finalize in-flight ticks first so the host
                            # carries (emission dedupe, regime carry) in the
                            # snapshot are consistent with the device state
                            await self.flush_pending()
                            # device fetch + np.savez of ~65 MB of buffers:
                            # keep it off the event loop so ws clients and
                            # ping deadlines aren't starved during the save
                            await asyncio.to_thread(
                                self.checkpoint.maybe_save, self
                            )
                    elif self._pending:
                        # no new candles this interval but a dispatched tick
                        # is still riding the pipeline: finalize it now.
                        # Without this, a quiet feed would delay the last
                        # burst's signals until the NEXT candle arrives
                        # (up to a full 5m bar — or forever on a stall).
                        last_tick = time.monotonic()
                        await self.flush_pending()
            except asyncio.CancelledError:
                raise
            except Exception:
                logging.exception("tick processing failed; continuing")
                # an async device fault surfaces here (first use of the
                # failed launch's outputs) — after donation the state may
                # be unrecoverable; reset cold instead of crash-looping
                self.recover_if_state_poisoned()
