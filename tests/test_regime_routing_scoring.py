"""Routing mask, context scoring, grid-only policy, time-of-day filter.

Oracles re-derive the reference decision logic (regime_routing.py:22-76,
context_scoring.py:39-114, signal_context_scorer.py:15-29,
grid_only_policy.py:121-158, time_of_day_filter.py:55-76) on scalars.
"""

from datetime import datetime, timezone

import jax.numpy as jnp
import numpy as np
import pytest

from binquant_tpu.enums import (
    MarketRegimeCode,
    MicroRegimeCode,
    MicroTransitionCode,
)
from binquant_tpu.regime import (
    DEFAULT_REGIME_STABILITY_S,
    GridOnlyPolicy,
    ScorerWeights,
    adjust_score,
    allows_long_autotrade_mask,
    evaluate_context_score,
    is_autotrade_suppressed,
    is_quiet_hours,
    is_regime_stable,
    long_autotrade_decision,
    score_signal_candidate,
)
from binquant_tpu.regime.context import MarketContext, SymbolFeatureArrays
from binquant_tpu.schemas import MarketBreadthSeries

S = 6


def mk_features(n=S, **over):
    S_ = n
    base = dict(
        valid=np.ones(S_, dtype=bool),
        timestamp=np.full(S_, 1000, np.int32),
        close=np.full(S_, 10.0, np.float32),
        return_pct=np.zeros(S_, np.float32),
        ema20=np.full(S_, 10.0, np.float32),
        ema50=np.full(S_, 10.0, np.float32),
        above_ema20=np.ones(S_, dtype=bool),
        above_ema50=np.ones(S_, dtype=bool),
        trend_score=np.zeros(S_, np.float32),
        relative_strength_vs_btc=np.zeros(S_, np.float32),
        atr_pct=np.full(S_, 0.01, np.float32),
        bb_width=np.full(S_, 0.03, np.float32),
        micro_regime=np.full(S_, int(MicroRegimeCode.RANGE), np.int32),
        micro_regime_strength=np.full(S_, 0.6, np.float32),
        micro_transition=np.full(S_, -1, np.int32),
        micro_transition_strength=np.zeros(S_, np.float32),
    )
    base.update(over)
    return SymbolFeatureArrays(**{k: jnp.asarray(v) for k, v in base.items()})


def mk_context(n=S, **over):
    ts = 100_000
    base = dict(
        valid=True,
        timestamp=np.int32(ts),
        fresh_count=np.int32(50),
        total_tracked_symbols=np.int32(50),
        coverage_ratio=1.0,
        btc_present=True,
        advancers=np.int32(25),
        decliners=np.int32(20),
        advancers_ratio=0.5,
        decliners_ratio=0.4,
        advancers_decliners_ratio=1.25,
        average_return=0.001,
        average_relative_strength_vs_btc=0.0,
        pct_above_ema20=0.55,
        pct_above_ema50=0.5,
        average_trend_score=0.001,
        average_atr_pct=0.015,
        average_bb_width=0.04,
        btc_return=0.002,
        btc_trend_score=0.001,
        btc_regime_score=0.05,
        market_stress_score=0.1,
        long_tailwind=0.2,
        short_tailwind=-0.1,
        market_regime=np.int32(MarketRegimeCode.RANGE),
        previous_market_regime=np.int32(MarketRegimeCode.RANGE),
        market_regime_transition=np.int32(-1),
        market_regime_transition_strength=0.0,
        long_regime_score=0.3,
        short_regime_score=0.2,
        range_regime_score=0.6,
        stress_regime_score=0.1,
        regime_is_transitioning=False,
        regime_stable_since=np.int32(ts - DEFAULT_REGIME_STABILITY_S - 10),
        features=mk_features(n),
    )
    base.update(over)
    conv = {
        k: (v if isinstance(v, SymbolFeatureArrays) else jnp.asarray(v))
        for k, v in base.items()
    }
    return MarketContext(**conv)


# ---------------------------------------------------------------------------
# Routing
# ---------------------------------------------------------------------------


def test_stable_range_regime_allows_long():
    ctx = mk_context()
    mask = np.asarray(allows_long_autotrade_mask(ctx))
    assert mask.all()
    allowed, reason = long_autotrade_decision(ctx, 0)
    assert allowed and reason.startswith("micro_regime_range")


@pytest.mark.parametrize(
    "over,expect_reason",
    [
        (dict(regime_is_transitioning=True), "regime_transitioning"),
        (dict(regime_stable_since=np.int32(-1)), "regime_stability_unknown"),
        (
            dict(regime_stable_since=np.int32(100_000 - 60)),
            "regime_unstable",
        ),
        (
            dict(market_regime=np.int32(MarketRegimeCode.HIGH_STRESS)),
            "market_regime_high_stress",
        ),
        (
            dict(market_regime=np.int32(MarketRegimeCode.TREND_DOWN)),
            "market_regime_trend_down",
        ),
        (dict(market_stress_score=0.4), "market_stress_elevated"),
        (dict(valid=False), "market_context_unavailable"),
    ],
)
def test_market_level_blocks(over, expect_reason):
    ctx = mk_context(**over)
    assert not np.asarray(allows_long_autotrade_mask(ctx)).any()
    allowed, reason = long_autotrade_decision(ctx, 0)
    assert not allowed
    assert reason.startswith(expect_reason)


def test_micro_level_blocks_and_recovery():
    micro = np.full(S, int(MicroRegimeCode.RANGE), np.int32)
    micro[1] = int(MicroRegimeCode.VOLATILE)
    micro[2] = int(MicroRegimeCode.TREND_DOWN)
    micro[3] = int(MicroRegimeCode.TREND_DOWN)
    trans = np.full(S, -1, np.int32)
    trans[3] = int(MicroTransitionCode.RECOVERY)
    valid = np.ones(S, dtype=bool)
    valid[4] = False  # falls back to market-level policy (RANGE -> allowed)
    ctx = mk_context(features=mk_features(micro_regime=micro, micro_transition=trans, valid=valid))
    mask = np.asarray(allows_long_autotrade_mask(ctx))
    assert mask[0]  # RANGE micro
    assert not mask[1]  # VOLATILE
    assert not mask[2]  # TREND_DOWN, no recovery
    assert mask[3]  # TREND_DOWN + RECOVERY
    assert mask[4]  # no features -> market regime RANGE
    assert not long_autotrade_decision(ctx, 1)[0]
    assert long_autotrade_decision(ctx, 3)[0]
    assert long_autotrade_decision(ctx, 4)[0]


def test_is_regime_stable_threshold():
    assert bool(is_regime_stable(mk_context()))
    young = mk_context(regime_stable_since=np.int32(100_000 - 100))
    assert not bool(is_regime_stable(young))


# ---------------------------------------------------------------------------
# Context scoring (oracle on scalars)
# ---------------------------------------------------------------------------


def clamp(v, lo=-1.0, hi=1.0):
    return max(lo, min(hi, float(v)))


def nneg(v):
    return max(0.0, float(v))


def oracle_score(ctx, direction, rs, trend):
    """context_scoring.py:39-114 on scalars."""
    short = direction == "SHORT"
    breadth = float(ctx.short_tailwind if short else ctx.long_tailwind)
    btc = float(ctx.btc_regime_score)
    btc_align = clamp(-btc) if short else clamp(btc)
    rs_s, tr_s = (-rs, -trend) if short else (rs, trend)
    cross = clamp(0.6 * rs_s + 0.4 * tr_s)
    override = clamp(0.6 * nneg(rs_s) + 0.4 * nneg(tr_s), 0.0, 1.0)
    stress = float(ctx.market_stress_score)
    dstress = stress * 0.35 if short else -stress
    sup = clamp(0.35 * breadth + 0.25 * btc_align + 0.25 * cross + 0.15 * dstress)
    fol = clamp(0.45 * breadth + 0.3 * btc_align + 0.25 * cross)
    risk = clamp(0.55 * stress + 0.25 * nneg(-sup) + 0.2 * (1 - override), 0.0, 1.0)
    if not short and breadth < 0 and override > 0:
        sup = clamp(sup + 0.2 * override)
        fol = clamp(fol + 0.15 * override)
    if short and breadth < 0 and override > 0:
        sup = clamp(sup + 0.1 * override)
    return sup, fol, risk, override, cross, btc_align, breadth


@pytest.mark.parametrize("direction", ["LONG", "SHORT"])
@pytest.mark.parametrize("rs,trend", [(0.02, 0.01), (-0.03, -0.005), (0.0, 0.0)])
def test_context_score_matches_oracle(direction, rs, trend):
    ctx = mk_context(long_tailwind=-0.15, short_tailwind=0.1, market_stress_score=0.2)
    rs_a = jnp.full((S,), rs, dtype=jnp.float32)
    tr_a = jnp.full((S,), trend, dtype=jnp.float32)
    cs = evaluate_context_score(ctx, jnp.asarray(direction == "SHORT"), rs_a, tr_a)
    sup, fol, risk, override, cross, btc_align, breadth = oracle_score(
        ctx, direction, rs, trend
    )
    np.testing.assert_allclose(float(np.asarray(cs.supportiveness_score)[0]), sup, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(np.asarray(cs.followthrough_score)[0]), fol, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(np.asarray(cs.adverse_excursion_risk)[0]), risk, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(np.asarray(cs.override_strength)[0]), override, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(np.asarray(cs.cross_asset_confirmation)[0]), cross, rtol=1e-5, atol=1e-6)

    # adjust_score formula (signal_context_scorer.py:15-29)
    w = ScorerWeights()
    adj = adjust_score(jnp.asarray(1.0), cs, w)
    expected = 1.0 + 1.0 * (fol + 0.35 * sup - 0.5 * risk)
    np.testing.assert_allclose(float(np.asarray(adj)[0]), expected, rtol=1e-5, atol=1e-6)


def test_invalid_context_gives_empty_score():
    ctx = mk_context(valid=False)
    cs = evaluate_context_score(
        ctx, jnp.asarray(False), jnp.zeros(S), jnp.zeros(S)
    )
    for name in cs._fields:
        np.testing.assert_allclose(np.asarray(getattr(cs, name)), 0.0, atol=1e-7)
    adj = adjust_score(jnp.asarray(0.7), cs)
    np.testing.assert_allclose(np.asarray(adj), 0.7, atol=1e-7)


def test_score_signal_candidate_emit_threshold():
    ctx = mk_context()
    ev = score_signal_candidate(
        ctx,
        jnp.asarray(False),
        jnp.asarray(0.5),
        jnp.zeros(S),
        jnp.zeros(S),
        emit_threshold=0.55,
    )
    emit = np.asarray(ev.emit)
    adjusted = np.asarray(ev.adjusted_score)
    assert emit.shape == adjusted.shape
    np.testing.assert_array_equal(emit, adjusted >= 0.55)


# ---------------------------------------------------------------------------
# Grid-only policy
# ---------------------------------------------------------------------------


def breadth_series(ma=None, raw=None, ts=None):
    n = len(ts or [])
    return MarketBreadthSeries(
        timestamp=ts or [],
        market_breadth=raw or [0.0] * n,
        market_breadth_ma=ma or [0.0] * n,
        adp=[0.0] * n,
        adp_ma=[0.0] * n,
        advancers=[0.0] * n,
        decliners=[0.0] * n,
    )


def test_grid_policy_activates_on_momentum():
    b = breadth_series(ma=[0.5, 0.6], ts=[1, 2])
    p = GridOnlyPolicy.resolve(int(MarketRegimeCode.RANGE), b)
    assert p.allow_grid_ladder and p.block_standard_bots
    assert p.direction == "toward_trend"
    assert p.source == "market_breadth_ma"
    np.testing.assert_allclose(p.momentum_points, 10.0)

    p2 = GridOnlyPolicy.resolve(
        int(MarketRegimeCode.TRANSITIONAL), breadth_series(ma=[0.6, 0.5], ts=[1, 2])
    )
    assert p2.allow_grid_ladder and p2.direction == "toward_range"


def test_grid_policy_disabled_paths():
    b = breadth_series(ma=[0.5, 0.6], ts=[1, 2])
    assert GridOnlyPolicy.resolve(None, b).reason == "market_context_unavailable"
    assert GridOnlyPolicy.resolve(-1, b).reason == "market_regime_unavailable"
    p = GridOnlyPolicy.resolve(int(MarketRegimeCode.TREND_UP), b)
    assert not p.allow_grid_ladder and p.reason == "market_regime_trend_up"
    flat = breadth_series(ma=[0.5, 0.5], ts=[1, 2])
    assert (
        GridOnlyPolicy.resolve(int(MarketRegimeCode.RANGE), flat).reason
        == "breadth_momentum_flat"
    )
    assert (
        GridOnlyPolicy.resolve(int(MarketRegimeCode.RANGE), None).reason
        == "breadth_momentum_unavailable"
    )


def test_grid_policy_timestamp_ordering_beats_list_order():
    # series delivered newest-first with timestamps: sorting must win
    b = breadth_series(ma=[0.7, 0.5], ts=[200, 100])
    p = GridOnlyPolicy.resolve(int(MarketRegimeCode.RANGE), b)
    # ordered -> [0.5 (ts100), 0.7 (ts200)] -> momentum toward trend
    assert p.direction == "toward_trend"
    assert p.latest == 0.7


# ---------------------------------------------------------------------------
# Time-of-day filter
# ---------------------------------------------------------------------------


def ldn(hour):
    # July: London = UTC+1, so UTC hour-1 == London hour
    return datetime(2026, 7, 20, hour - 1, 30, tzinfo=timezone.utc)


def test_quiet_hours_window():
    assert is_quiet_hours(ldn(20))
    assert is_quiet_hours(ldn(22))
    assert not is_quiet_hours(ldn(23))
    assert not is_quiet_hours(ldn(12))


def test_suppression_and_trend_override():
    # mid-day: never suppressed
    assert not is_autotrade_suppressed(int(MarketRegimeCode.RANGE), 0.0, ldn(12))
    # quiet hours, RANGE: suppressed
    assert is_autotrade_suppressed(int(MarketRegimeCode.RANGE), 0.9, ldn(21))
    # quiet hours, strong stable trend: allowed
    assert not is_autotrade_suppressed(int(MarketRegimeCode.TREND_UP), 0.75, ldn(21))
    # weak trend: suppressed
    assert is_autotrade_suppressed(int(MarketRegimeCode.TREND_UP), 0.5, ldn(21))
    # no context: suppressed
    assert is_autotrade_suppressed(None, 1.0, ldn(21))
