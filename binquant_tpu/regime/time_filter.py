"""Time-of-day autotrade filter (host edge).

Covers the reference's ``shared/time_of_day_filter.py`` surface: autotrade
activations are suppressed inside the 20:00–23:00 London quiet window
unless the market is in a strong, stable trend. The decision is
wall-clock-dependent by design, so it stays host-side; the device-side
tick step applies the SAME strong-trend override against the context
computed that tick (engine/step.py imports the constants below), and the
oracle A/B mirrors this module — three consumers, one set of constants.

Structure mirrors the repo's other host-edge policies (grid_policy,
routing): a frozen decision value (:class:`QuietHoursDecision`) produced
by one resolver, with thin boolean helpers kept for the existing call
sites, and the structured Telegram block template preserved verbatim so
downstream parsers stay uniform.
"""

from __future__ import annotations

import os
from datetime import datetime
from typing import NamedTuple
from zoneinfo import ZoneInfo

from binquant_tpu.enums import MarketRegimeCode, MarketTransitionCode

LONDON = ZoneInfo("Europe/London")

# The quiet window, London local hours: [start, end).
QUIET_START_HOUR = 20
QUIET_END_HOUR = 23

# Strong-stable-trend override inputs (time_of_day_filter.py:45-46).
# Public: the device-side tick step applies the same override against the
# CURRENT tick's context (engine/step.py), exactly as the reference reads
# the live context (time_of_day_filter.py:60-76).
OVERRIDE_REGIMES = {int(MarketRegimeCode.TREND_UP), int(MarketRegimeCode.TREND_DOWN)}
MIN_TRANSITION_STRENGTH = 0.7


class QuietHoursDecision(NamedTuple):
    """Resolved quiet-hours verdict for one instant + context snapshot."""

    suppressed: bool
    in_window: bool  # wall clock inside the London quiet window
    override: bool  # strong-stable-trend override engaged
    reason: str  # short machine-readable cause


def _as_london(now: datetime | None = None) -> datetime:
    return (now or datetime.now(tz=LONDON)).astimezone(LONDON)


def is_quiet_hours(now: datetime | None = None) -> bool:
    """True when London-local hour is within [QUIET_START_HOUR, QUIET_END_HOUR)."""
    return QUIET_START_HOUR <= _as_london(now).hour < QUIET_END_HOUR


def resolve_quiet_hours(
    market_regime: int | None,
    transition_strength: float,
    now: datetime | None = None,
) -> QuietHoursDecision:
    """Full quiet-hours resolution (time_of_day_filter.py:60-76 semantics).

    ``market_regime`` is the device int code; None / negative means no
    valid context, which always suppresses inside the window. The override
    requires BOTH a trending regime and transition strength at or above
    :data:`MIN_TRANSITION_STRENGTH`.
    """
    if not is_quiet_hours(now):
        return QuietHoursDecision(
            suppressed=False, in_window=False, override=False, reason="outside_window"
        )
    if market_regime is None or market_regime < 0:
        return QuietHoursDecision(
            suppressed=True, in_window=True, override=False, reason="no_context"
        )
    if market_regime in OVERRIDE_REGIMES and (
        transition_strength >= MIN_TRANSITION_STRENGTH
    ):
        return QuietHoursDecision(
            suppressed=False, in_window=True, override=True, reason="strong_trend"
        )
    return QuietHoursDecision(
        suppressed=True, in_window=True, override=False, reason="quiet_window"
    )


def is_autotrade_suppressed(
    market_regime: int | None,
    transition_strength: float,
    now: datetime | None = None,
) -> bool:
    """Boolean view of :func:`resolve_quiet_hours` (the legacy call shape
    the oracle and the host emission edge consume)."""
    return resolve_quiet_hours(market_regime, transition_strength, now).suppressed


def _regime_name(market_regime: int | None) -> str:
    if market_regime is None or market_regime < 0:
        return "UNAVAILABLE"
    return MarketRegimeCode(market_regime).name


def _transition_name(transition: int | None) -> str:
    if transition is None or transition < 0:
        return "None"
    return MarketTransitionCode(transition).name


def _fmt3(value: float | None) -> str:
    return f"{value:.3f}" if value is not None else "n/a"


def build_quiet_hours_signal_msg(
    symbol: str,
    algo: str,
    side: str,
    market_regime: int | None,
    transition: int | None,
    transition_strength: float | None,
    stress: float | None,
    now: datetime | None = None,
) -> str:
    """Structured Telegram alert for a suppressed activation
    (time_of_day_filter.py:79-114). The key/value line shape is
    load-bearing — downstream Telegram parsers key on it."""
    london_now = _as_london(now)
    return f"""
        - [{os.getenv("ENV", "")}] <strong>#time_of_day_block</strong>
        - Symbol: {symbol}
        - Algorithm: {algo}
        - Side: {side}
        - Reason: London time {london_now.strftime("%H:%M")} falls in the {QUIET_START_HOUR:02d}:00-{QUIET_END_HOUR:02d}:00 quiet window
        - Market regime: {_regime_name(market_regime)}
        - Market transition: {_transition_name(transition)}
        - Transition strength: {_fmt3(transition_strength)}
        - Market stress: {_fmt3(stress)}
        - Action: autotrade suppressed (signal kept as alert only)
    """
