"""Benchmark: full-suite tick latency over the symbol batch.

Measures the end-to-end per-tick latency of the jit'd engine step (buffer
update → indicators → market context/regimes → all 14 strategy kernels →
trigger-mask D2H) at the north-star scale: 2000 symbols × 400-bar windows on
one chip (BASELINE.json: p99 < 50 ms @ 1 s ticks). Prints ONE JSON line:

    {"metric": "tick_p99_ms", "value": N, "unit": "ms", "vs_baseline": R}

``vs_baseline`` is the target budget ratio 50ms/value (>1 beats the
north-star; the reference itself is O(100ms–1s) *per symbol* serial —
SURVEY.md §6 — so any sub-50ms full-batch tick is ≥4 orders of magnitude
over the reference pipeline).

``--smoke`` runs tiny shapes for CI/CPU sanity.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def run(num_symbols: int, window: int, ticks: int, warmup: int) -> dict:
    import jax

    from binquant_tpu.engine.buffer import NUM_FIELDS, Field
    from binquant_tpu.engine.step import (
        default_host_inputs,
        initial_engine_state,
        pad_updates,
        tick_step,
    )
    from binquant_tpu.regime.context import ContextConfig

    rng = np.random.default_rng(7)
    cfg = ContextConfig()
    state = initial_engine_state(num_symbols, window=window)

    # preload full windows so the bench measures steady state
    t0 = 1_753_000_000
    px = 20.0 + rng.random(num_symbols).astype(np.float32) * 100

    def make_updates(ts_s: int, px: np.ndarray):
        rows = np.arange(num_symbols, dtype=np.int32)
        ts = np.full(num_symbols, ts_s, dtype=np.int32)
        closes = px * (1 + rng.normal(0, 0.004, num_symbols))
        vals = np.zeros((num_symbols, NUM_FIELDS), dtype=np.float32)
        vals[:, Field.OPEN] = px
        vals[:, Field.CLOSE] = closes
        vals[:, Field.HIGH] = np.maximum(px, closes) * 1.002
        vals[:, Field.LOW] = np.minimum(px, closes) * 0.998
        vals[:, Field.VOLUME] = np.abs(rng.normal(1000, 150, num_symbols))
        vals[:, Field.QUOTE_VOLUME] = vals[:, Field.VOLUME] * closes
        vals[:, Field.NUM_TRADES] = 150
        vals[:, Field.DURATION_S] = 900
        return rows, ts, vals, closes

    from binquant_tpu.engine.buffer import apply_updates

    for b in range(window):
        rows, ts, vals, px = make_updates(t0 + b * 900, px)
        state = state._replace(
            buf5=apply_updates(state.buf5, rows, ts, vals),
            buf15=apply_updates(state.buf15, rows, ts, vals),
        )
    import jax.numpy as jnp

    tracked = np.ones(num_symbols, dtype=bool)
    latencies = []
    now = t0 + window * 900
    for i in range(warmup + ticks):
        rows, ts, vals, px = make_updates(now + i * 900, px)
        upd = pad_updates(rows, ts, vals, size=num_symbols)
        inputs = default_host_inputs(num_symbols)._replace(
            tracked=jnp.asarray(tracked),
            btc_row=np.int32(0),
            timestamp_s=np.int32(now + i * 900),
            timestamp5_s=np.int32(now + i * 900),
        )
        start = time.perf_counter()
        state, out = tick_step(state, upd, upd, inputs, cfg)
        # the tiny D2H the host actually needs: ONE packed trigger summary
        triggers = np.asarray(out.summary.trigger)
        _ = int(np.asarray(out.context.market_regime))
        elapsed = (time.perf_counter() - start) * 1000.0
        if i >= warmup:
            latencies.append(elapsed)
        del triggers

    lat = np.array(latencies)
    return {
        "p50_ms": float(np.percentile(lat, 50)),
        "p99_ms": float(np.percentile(lat, 99)),
        "mean_ms": float(lat.mean()),
        "symbol_evals_per_sec": float(num_symbols * 14 / (lat.mean() / 1000.0)),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="tiny shapes")
    parser.add_argument("--symbols", type=int, default=2048)
    parser.add_argument("--window", type=int, default=400)
    parser.add_argument("--ticks", type=int, default=30)
    parser.add_argument("--warmup", type=int, default=5)
    args = parser.parse_args()

    if args.smoke:
        args.symbols, args.window, args.ticks, args.warmup = 32, 120, 5, 2

    stats = run(args.symbols, args.window, args.ticks, args.warmup)
    value = round(stats["p99_ms"], 3)
    print(
        json.dumps(
            {
                "metric": "tick_p99_ms",
                "value": value,
                "unit": "ms",
                "vs_baseline": round(50.0 / value, 3) if value > 0 else 0.0,
                "detail": {
                    "symbols": args.symbols,
                    "window": args.window,
                    "p50_ms": round(stats["p50_ms"], 3),
                    "mean_ms": round(stats["mean_ms"], 3),
                    "symbol_strategy_evals_per_sec": round(
                        stats["symbol_evals_per_sec"]
                    ),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
