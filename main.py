"""Entrypoint: one asyncio event loop driving ingest + the TPU tick engine.

Equivalent of ``/root/reference/main.py``: websocket ingest and the consumer
loop joined by an asyncio.Queue, heartbeat per processed tick, per-message
crash isolation. The evaluation itself runs on device via
``binquant_tpu.engine.step.tick_step`` instead of per-symbol pandas.

Replay mode (``--replay file.jsonl``) feeds recorded klines through the
same pipeline with network sinks stubbed — the offline correctness/bench
harness (BASELINE.json config #2).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys


def configure_logging(level: str = "INFO") -> None:
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )


async def run_live() -> None:
    from binquant_tpu.config import Config
    from binquant_tpu.io.autotrade import AutotradeConsumer
    from binquant_tpu.io.binbot import BinbotApi
    from binquant_tpu.io.exchanges import KucoinFutures
    from binquant_tpu.io.pipeline import SignalEngine
    from binquant_tpu.io.telegram import TelegramConsumer
    from binquant_tpu.io.websocket import WebsocketClientFactory

    config = Config()
    configure_logging(config.log_level)
    binbot_api = BinbotApi(config.binbot_api_url)

    autotrade_settings = binbot_api.get_autotrade_settings()
    test_settings = binbot_api.get_test_autotrade_settings()
    all_symbols = binbot_api.get_symbols()
    telegram_consumer = TelegramConsumer(
        token=config.telegram_bot_token, chat_id=config.telegram_user_id
    )
    at_consumer = AutotradeConsumer(
        autotrade_settings=autotrade_settings,
        active_test_bots=binbot_api.get_active_pairs("paper_trading"),
        all_symbols=all_symbols,
        test_autotrade_settings=test_settings,
        active_grid_ladders=binbot_api.get_active_grid_ladders(),
        binbot_api=binbot_api,
    )
    engine = SignalEngine(
        config=config,
        binbot_api=binbot_api,
        telegram_consumer=telegram_consumer,
        at_consumer=at_consumer,
        futures_api=KucoinFutures(),
        window=config.window_bars,
    )

    # Seed both interval buffers with REST history so strategies can fire
    # on the first live tick (klines_provider.py:278-293) instead of being
    # blind for MIN_BARS * 15m after a cold start.
    from binquant_tpu.io.exchanges import (
        BinanceApi,
        KucoinApi,
        make_history_fetcher,
    )
    from binquant_tpu.io.websocket import filter_fiat_symbols

    exchange_id = str(autotrade_settings.exchange_id)
    history_api = (
        KucoinApi() if exchange_id.lower().startswith("kucoin") else BinanceApi()
    )
    tracked = [s.id for s in filter_fiat_symbols(all_symbols)]
    engine.backfill(tracked, make_history_fetcher(history_api, exchange_id))

    queue: asyncio.Queue = asyncio.Queue()
    factory = WebsocketClientFactory(
        queue,
        all_symbols,
        exchange_id=exchange_id,
        market_type=getattr(
            autotrade_settings.market_type, "value", autotrade_settings.market_type
        ),
    )
    connector = factory.create_connector()
    await connector.start_stream()
    logging.info("binquant_tpu started: %d symbols tracked", len(all_symbols))
    await engine.consume_loop(queue)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--replay", help="JSONL kline file for offline replay")
    parser.add_argument("--replay-report", action="store_true")
    args = parser.parse_args()

    if args.replay:
        from binquant_tpu.io.replay import run_replay

        stats = run_replay(args.replay)
        print(stats)
        return 0

    asyncio.run(run_live())
    return 0


if __name__ == "__main__":
    sys.exit(main())
