"""Websocket ingest: chunked multi-client kline streams.

Equivalent of ``/root/reference/producers/klines_connector.py`` and
``/root/reference/shared/streaming/websocket_factory.py``: symbols are
chunked across N websocket connections (400/client Binance, 300/connection
KuCoin), frames are JSON-parsed, **closed candles only** are pushed onto the
asyncio queue as ``KlineProduceModel`` dicts, and a closed socket triggers
reconnect-and-resubscribe. Uses the ``websockets`` library; the connection
factory is injectable so tests drive the parser with fake frames.

The richer ``ExtendedKline`` fields (quote volume, trade count, taker-buy
splits) are captured here too — the reference drops them at the connector
(KlineProduceModel has only OHLCV) and several strategies then lack them on
the 5m path; the TPU buffer keeps the full payload.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import time
from collections import deque
from collections.abc import Callable
from typing import Any

from binquant_tpu.exceptions import WebSocketError
from binquant_tpu.obs.events import get_event_log
from binquant_tpu.obs.instruments import (
    WS_FRAMES,
    WS_PARSE_ERRORS,
    WS_RECONNECTS,
)
from binquant_tpu.schemas import SymbolModel

BINANCE_WS_BASE = "wss://stream.binance.com:9443/ws"
MAX_MARKETS_PER_CLIENT = 400  # Binance (klines_connector.py:24)
MAX_TOPICS_PER_CONNECTION = 300  # KuCoin (websocket_factory.py:30)

FIAT_PREFIXES = ("USDT", "USDC", "BUSD", "EUR", "TRY", "DAI")

# Reconnect backoff defaults shared by both exchange connectors. The ±25%
# per-client jitter exists because the N chunked clients of one exchange
# share one deterministic exponential schedule: an exchange-wide outage
# would otherwise end in a synchronized resubscribe thundering herd.
RECONNECT_INITIAL_BACKOFF_S = 1.0
RECONNECT_MAX_BACKOFF_S = 30.0
RECONNECT_JITTER = 0.25


def reconnect_delay(
    backoff: float, rng: random.Random, jitter: float = RECONNECT_JITTER
) -> float:
    """``backoff`` spread by ±``jitter`` fraction via the client's own rng
    (seeded per client in tests via ``reconnect_seed``)."""
    if jitter <= 0:
        return backoff
    return backoff * (1.0 + jitter * (2.0 * rng.random() - 1.0))


class _BadFrameMeter:
    """Counts ws parse failures (``bqt_ws_parse_errors_total``) and emits a
    rate-limited ``ws_bad_frame`` event — a poisoned-feed chaos run is
    observable without letting a frame-per-ms garbage storm turn the event
    log into a firehose. Suppressed emissions are tallied and reported on
    the next admitted event."""

    def __init__(self, every_s: float = 30.0) -> None:
        self.every_s = float(every_s)
        self._last: dict[str, float] = {}
        self._suppressed: dict[str, int] = {}

    def note(self, exchange: str, error: str, raw_len: int) -> None:
        WS_PARSE_ERRORS.labels(exchange=exchange).inc()
        now = time.monotonic()
        if now - self._last.get(exchange, float("-inf")) < self.every_s:
            self._suppressed[exchange] = self._suppressed.get(exchange, 0) + 1
            return
        self._last[exchange] = now
        get_event_log().emit(
            "ws_bad_frame",
            exchange=exchange,
            error=str(error)[:200],
            raw_len=int(raw_len),
            suppressed_since_last=self._suppressed.pop(exchange, 0),
        )


BAD_FRAMES = _BadFrameMeter()


class WsHealth:
    """Rolling reconnect-storm tracker surfaced as the ``ws`` section of
    ``/healthz`` (``SignalEngine.health_snapshot``). Connectors report
    drops and recoveries; a reconnect rate past ``degrade_reconnects``
    inside the window marks the probe ``degraded`` — which by the PR 1
    probe contract stays HTTP 200 (alive but impaired; only ``stale`` is
    503), so orchestrators see the storm without killing live engines."""

    def __init__(
        self, window_s: float = 300.0, degrade_reconnects: int = 6
    ) -> None:
        self.window_s = float(window_s)
        self.degrade_reconnects = int(degrade_reconnects)
        self._reconnects: deque[float] = deque(maxlen=4096)
        self._backoff: dict[str, float] = {}  # "exchange/client" -> seconds

    def note_reconnect(
        self, exchange: str, client: int, backoff_s: float,
        now: float | None = None,
    ) -> None:
        self._reconnects.append(
            time.monotonic() if now is None else float(now)
        )
        self._backoff[f"{exchange}/{client}"] = float(backoff_s)

    def note_connected(self, exchange: str, client: int) -> None:
        self._backoff.pop(f"{exchange}/{client}", None)

    def reset(self) -> None:
        self._reconnects.clear()
        self._backoff.clear()

    def snapshot(self, now: float | None = None) -> dict:
        now = time.monotonic() if now is None else float(now)
        recent = sum(1 for t in self._reconnects if now - t <= self.window_s)
        return {
            "reconnects_recent": recent,
            "window_s": self.window_s,
            "degrade_reconnects": self.degrade_reconnects,
            "clients_backing_off": len(self._backoff),
            "max_backoff_s": max(self._backoff.values(), default=0.0),
            "storming": recent >= self.degrade_reconnects,
        }


# Process singleton the connectors feed and health_snapshot reads.
# Env-configured directly (not Config: this module is imported by tests
# and tools that never construct the validated config singleton).
WS_HEALTH = WsHealth(
    window_s=float(os.environ.get("BQT_WS_DEGRADE_WINDOW", "300") or "300"),
    degrade_reconnects=int(
        os.environ.get("BQT_WS_DEGRADE_RECONNECTS", "6") or "6"
    ),
)


def filter_fiat_symbols(symbols: list[SymbolModel]) -> list[SymbolModel]:
    """Drop fiat-to-fiat pairs (websocket_factory.py:49)."""
    return [
        s
        for s in symbols
        if s.active and not any(s.id.startswith(p) for p in FIAT_PREFIXES)
    ]


def kucoin_spot_api_symbol(s: SymbolModel) -> str:
    """Engine id → dashed KuCoin spot form (``BTC-USDT``). Shared by the
    websocket topic builder and the REST history backfill — the two
    universes must never drift apart (a mismatch silently loads/streams
    zero bars for the affected symbols)."""
    if not s.base_asset:
        # an undashed id is NOT a valid KuCoin symbol: the ws subscribe
        # fails silently (response=False) and REST raises per symbol —
        # surface the bad symbol payload instead of quietly losing it
        logging.warning(
            "symbol %s has no base_asset; KuCoin spot form unknown", s.id
        )
        return s.id
    return f"{s.base_asset}-{s.quote_asset}"


def kucoin_futures_ids(symbols: list[SymbolModel]) -> list[str]:
    """The KuCoin futures universe: *USDTM contract ids
    (websocket_factory.py:93). Shared by ws subscription and backfill."""
    return [s.id for s in symbols if s.id.endswith("USDTM")]


def parse_binance_kline_frame(raw: str | bytes) -> dict | None:
    """One frame → ExtendedKline-shaped dict for closed candles, else None
    (klines_connector.py:148-164 + the extra payload fields)."""
    try:
        res = json.loads(raw)
    except Exception as e:
        BAD_FRAMES.note("binance", str(e), len(str(raw)))
        logging.error("Failed to decode ws message: %s; len=%s", e, len(str(raw)))
        return None
    if res.get("e") != "kline":
        logging.debug("Non-kline event received: %s", res.get("e"))
        return None
    k = res.get("k", {})
    if not k.get("s") or not k.get("x"):  # closed candles only
        return None
    try:
        return {
            "symbol": k["s"],
            "open_time": int(k["t"]),
            "close_time": int(k["T"]),
            "open": float(k["o"]),
            "high": float(k["h"]),
            "low": float(k["l"]),
            "close": float(k["c"]),
            "volume": float(k["v"]),
            "quote_asset_volume": float(k.get("q", 0.0)),
            "number_of_trades": float(k.get("n", 0.0)),
            "taker_buy_base_volume": float(k.get("V", 0.0)),
            "taker_buy_quote_volume": float(k.get("Q", 0.0)),
            # source tag for the ingest monitor's per-exchange feed-lag
            # watermarks (additive — the batcher ignores unknown keys)
            "exchange": "binance",
        }
    except (TypeError, ValueError, KeyError) as e:
        # valid JSON, malformed fields: a SHAPE parse failure. Must not
        # escape — it would tear down the whole multi-market connection
        # as a phantom reconnect instead of counting as a bad frame.
        BAD_FRAMES.note("binance", f"bad kline fields: {e}", len(str(raw)))
        logging.error("Malformed kline frame fields: %s", e)
        return None


class KlinesConnector:
    """Binance kline streams over N chunked connections with reconnect.

    Subscribes BOTH engine intervals (5m + 15m) per symbol: the engine's
    dual buffers each need live frames (the reference re-fetches both
    interval histories per message instead — klines_provider.py:201-210);
    a 15m-only subscription starves buf5 and silences the 5m strategies.
    """

    def __init__(
        self,
        queue: asyncio.Queue,
        symbols: list[SymbolModel],
        intervals: tuple[str, ...] = ("5m", "15m"),
        connect: Callable[..., Any] | None = None,
        max_markets_per_client: int = MAX_MARKETS_PER_CLIENT,
        reconnect_jitter: float = RECONNECT_JITTER,
        reconnect_seed: int | None = None,
        initial_backoff_s: float = RECONNECT_INITIAL_BACKOFF_S,
        max_backoff_s: float = RECONNECT_MAX_BACKOFF_S,
        health: WsHealth | None = None,
    ) -> None:
        self.queue = queue
        self.symbols = filter_fiat_symbols(symbols)
        self.intervals = intervals
        self.max_markets_per_client = max_markets_per_client
        if connect is None:
            import websockets

            connect = websockets.connect
        self._connect = connect
        self._tasks: list[asyncio.Task] = []
        self._reconnect_jitter = reconnect_jitter
        self._reconnect_seed = reconnect_seed
        self._initial_backoff_s = initial_backoff_s
        self._max_backoff_s = max_backoff_s
        self._health = health or WS_HEALTH

    def _client_rng(self, idx: int) -> random.Random:
        """Per-client jitter rng — seeded + offset when a test pins
        ``reconnect_seed`` (distinct per client either way, so a shared
        outage cannot resynchronize the fleet)."""
        if self._reconnect_seed is None:
            return random.Random()
        return random.Random(self._reconnect_seed + idx)

    def _chunks(self) -> list[list[str]]:
        """Chunk SYMBOLS so each client stays under the stream cap with
        every interval subscribed."""
        per_client = max(self.max_markets_per_client // len(self.intervals), 1)
        chunks = []
        for i in range(0, len(self.symbols), per_client):
            chunk = self.symbols[i : i + per_client]
            chunks.append(
                [
                    f"{s.id.lower()}@kline_{iv}"
                    for s in chunk
                    for iv in self.intervals
                ]
            )
        return chunks

    async def _run_client(self, idx: int, markets: list[str]) -> None:
        """One connection: subscribe, pump frames, reconnect on close
        (klines_connector.py:53-69) with per-client jittered backoff."""
        backoff = self._initial_backoff_s
        rng = self._client_rng(idx)
        while True:
            try:
                async with self._connect(BINANCE_WS_BASE) as ws:
                    await ws.send(
                        json.dumps(
                            {"method": "SUBSCRIBE", "params": markets, "id": 1}
                        )
                    )
                    logging.info(
                        "Subscribed client %d to %d markets", idx, len(markets)
                    )
                    backoff = self._initial_backoff_s
                    self._health.note_connected("binance", idx)
                    async for raw in ws:
                        WS_FRAMES.labels(exchange="binance").inc()
                        kline = parse_binance_kline_frame(raw)
                        if kline is not None:
                            await self.queue.put(kline)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                WS_RECONNECTS.labels(exchange="binance").inc()
                self._health.note_reconnect("binance", idx, backoff)
                get_event_log().emit(
                    "ws_reconnect",
                    exchange="binance",
                    client=idx,
                    error=str(e),
                    backoff_s=backoff,
                )
                logging.warning(
                    "ws client %d dropped (%s); reconnecting in %.0fs",
                    idx,
                    e,
                    backoff,
                )
                await asyncio.sleep(
                    reconnect_delay(backoff, rng, self._reconnect_jitter)
                )
                backoff = min(backoff * 2, self._max_backoff_s)

    async def start_stream(self) -> None:
        chunks = self._chunks()
        if not chunks:
            raise WebSocketError("no symbols to subscribe")
        for idx, markets in enumerate(chunks):
            self._tasks.append(
                asyncio.create_task(self._run_client(idx, markets))
            )

    async def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()


# ONE source of truth for interval naming (io/exchanges.py): ws topics and
# REST backfill must agree or symbols silently stream/load zero bars.
from binquant_tpu.io.exchanges import (  # noqa: E402
    INTERVAL_SECONDS,
    KUCOIN_INTERVALS as KUCOIN_WS_INTERVALS,
)

# KuCoin ws interval string -> seconds, derived from the shared tables
_KUCOIN_INTERVAL_S = {
    KUCOIN_WS_INTERVALS[k]: INTERVAL_SECONDS[k] for k in KUCOIN_WS_INTERVALS
}


def parse_kucoin_candle_message(
    raw: str | bytes, market_type: str
) -> tuple[str, str, dict] | None:
    """One KuCoin ws frame → (symbol, interval, candle dict) or None.

    Spot topic ``/market/candles:{sym}_{iv}`` carries
    ``data.candles = [time_s, open, close, high, low, volume, turnover]``;
    futures ``/contractMarket/limitCandle:{sym}_{iv}`` carries
    ``[time_s, open, high, low, close, volume]``. Both describe the candle
    in progress — closedness is decided by the caller when a newer open
    time appears (KucoinKlinesConnector._on_candle).
    """
    try:
        msg = json.loads(raw)
    except Exception as e:
        BAD_FRAMES.note("kucoin", str(e), len(str(raw)))
        logging.error("Failed to decode kucoin ws message: %s", e)
        return None
    if msg.get("type") != "message":
        return None
    topic = str(msg.get("topic", ""))
    data = msg.get("data") or {}
    candles = data.get("candles")
    if not candles or ":" not in topic:
        return None
    try:
        sym_iv = topic.split(":", 1)[1]
        symbol, interval = sym_iv.rsplit("_", 1)
    except ValueError:
        return None
    interval_s = _KUCOIN_INTERVAL_S.get(interval)
    if interval_s is None:
        return None
    try:
        t = int(float(candles[0])) * 1000
        if str(market_type).lower() == "futures":
            o, h, low, c = (float(candles[i]) for i in (1, 2, 3, 4))
            volume = float(candles[5]) if len(candles) > 5 else 0.0
            turnover = 0.0
        else:
            o, c, h, low = (float(candles[i]) for i in (1, 2, 3, 4))
            volume = float(candles[5]) if len(candles) > 5 else 0.0
            turnover = float(candles[6]) if len(candles) > 6 else 0.0
    except (TypeError, ValueError, IndexError) as e:
        # shape parse failure (see the Binance twin above): count it,
        # never let it tear down a 300-topic connection
        BAD_FRAMES.note("kucoin", f"bad candle fields: {e}", len(str(raw)))
        logging.error("Malformed kucoin candle fields: %s", e)
        return None
    return (
        symbol,
        interval,
        {
            "symbol": symbol.replace("-", ""),
            "open_time": t,
            "close_time": t + interval_s * 1000 - 1,
            "open": o,
            "high": h,
            "low": low,
            "close": c,
            "volume": volume,
            "quote_asset_volume": turnover,
            "number_of_trades": 0.0,
            "taker_buy_base_volume": 0.0,
            "taker_buy_quote_volume": 0.0,
            # source tag for the ingest monitor's per-exchange feed lag
            "exchange": "kucoin",
        },
    )


class KucoinKlinesConnector:
    """KuCoin spot/futures kline streams (websocket_factory.py:55-143).

    Protocol: POST the bullet endpoint for a token + ws endpoint, connect
    with ``?token=``, subscribe topics in batches of ≤300 per connection,
    answer the ping cadence the bullet response dictates. KuCoin pushes the
    *in-progress* candle; a candle is emitted as closed when a frame with a
    newer open time arrives for the same (symbol, interval).
    """

    SPOT_BULLET = "https://api.kucoin.com/api/v1/bullet-public"
    FUTURES_BULLET = "https://api-futures.kucoin.com/api/v1/bullet-public"

    def __init__(
        self,
        queue: asyncio.Queue,
        symbols: list[SymbolModel],
        market_type: str = "futures",
        intervals: tuple[str, ...] = ("5min", "15min"),
        connect: Callable[..., Any] | None = None,
        token_fetch: Callable[[], tuple[str, str, float]] | None = None,
        max_topics_per_connection: int = MAX_TOPICS_PER_CONNECTION,
        reconnect_jitter: float = RECONNECT_JITTER,
        reconnect_seed: int | None = None,
        initial_backoff_s: float = RECONNECT_INITIAL_BACKOFF_S,
        max_backoff_s: float = RECONNECT_MAX_BACKOFF_S,
        health: WsHealth | None = None,
    ) -> None:
        self.queue = queue
        self.market_type = market_type
        symbols = filter_fiat_symbols(symbols)
        if str(market_type).lower() == "futures":
            self.topic_symbols = kucoin_futures_ids(symbols)
        else:
            self.topic_symbols = [kucoin_spot_api_symbol(s) for s in symbols]
        self.intervals = intervals
        self.max_topics_per_connection = max_topics_per_connection
        if connect is None:
            import websockets

            connect = websockets.connect
        self._connect = connect
        self._token_fetch = token_fetch or self._default_token_fetch
        self._tasks: list[asyncio.Task] = []
        # (symbol, interval) -> last in-progress candle dict
        self._last_candle: dict[tuple[str, str], dict] = {}
        self._reconnect_jitter = reconnect_jitter
        self._reconnect_seed = reconnect_seed
        self._initial_backoff_s = initial_backoff_s
        self._max_backoff_s = max_backoff_s
        self._health = health or WS_HEALTH

    _client_rng = KlinesConnector._client_rng

    def _default_token_fetch(self) -> tuple[str, str, float]:
        """(ws_endpoint, token, ping_interval_s) via the public bullet."""
        import httpx

        url = (
            self.FUTURES_BULLET
            if str(self.market_type).lower() == "futures"
            else self.SPOT_BULLET
        )
        data = httpx.post(url, timeout=10).json()["data"]
        server = data["instanceServers"][0]
        return (
            server["endpoint"],
            data["token"],
            float(server.get("pingInterval", 18000)) / 1000.0,
        )

    def _topic(self, symbol: str, interval: str) -> str:
        if str(self.market_type).lower() == "futures":
            return f"/contractMarket/limitCandle:{symbol}_{interval}"
        return f"/market/candles:{symbol}_{interval}"

    def _chunks(self) -> list[list[str]]:
        topics = [
            self._topic(sym, iv)
            for sym in self.topic_symbols
            for iv in self.intervals
        ]
        n = self.max_topics_per_connection
        return [topics[i : i + n] for i in range(0, len(topics), n)]

    async def _on_candle(self, symbol: str, interval: str, candle: dict) -> None:
        """Track the in-progress candle; emit the previous one as closed
        when the open time advances."""
        key = (symbol, interval)
        prev = self._last_candle.get(key)
        if prev is not None and candle["open_time"] > prev["open_time"]:
            await self.queue.put(prev)
        self._last_candle[key] = candle

    async def _run_client(self, idx: int, topics: list[str]) -> None:
        backoff = self._initial_backoff_s
        rng = self._client_rng(idx)
        while True:
            try:
                # the bullet handshake is a blocking HTTP POST; keep it off
                # the event loop so other clients' pings aren't starved
                endpoint, token, ping_interval = await asyncio.to_thread(
                    self._token_fetch
                )
                url = f"{endpoint}?token={token}&connectId=bq{idx}"
                async with self._connect(url) as ws:
                    # Batch comma-joined suffixes (≤100/message): 300
                    # individual subscribes would blow KuCoin's ~100
                    # uplink-messages-per-10s limit, and with
                    # response=False the rejects are invisible.
                    prefix = topics[0].split(":", 1)[0]
                    suffixes = [t.split(":", 1)[1] for t in topics]
                    per_msg = 100
                    for i in range(0, len(suffixes), per_msg):
                        await ws.send(
                            json.dumps(
                                {
                                    "id": i // per_msg + 1,
                                    "type": "subscribe",
                                    "topic": (
                                        f"{prefix}:"
                                        + ",".join(suffixes[i : i + per_msg])
                                    ),
                                    "privateChannel": False,
                                    "response": False,
                                }
                            )
                        )
                        await asyncio.sleep(0.1)
                    logging.info(
                        "kucoin %s client %d subscribed %d topics",
                        self.market_type,
                        idx,
                        len(topics),
                    )
                    backoff = self._initial_backoff_s
                    self._health.note_connected("kucoin", idx)

                    async def ping_loop() -> None:
                        n = 0
                        while True:
                            await asyncio.sleep(ping_interval)
                            n += 1
                            await ws.send(
                                json.dumps({"id": f"ping{n}", "type": "ping"})
                            )

                    ping_task = asyncio.create_task(ping_loop())
                    try:
                        async for raw in ws:
                            WS_FRAMES.labels(exchange="kucoin").inc()
                            parsed = parse_kucoin_candle_message(
                                raw, self.market_type
                            )
                            if parsed is not None:
                                await self._on_candle(*parsed)
                    finally:
                        ping_task.cancel()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # Drop this client's in-progress candles: after an outage
                # that spans a bar boundary, the next frame's newer open
                # time would otherwise emit the pre-disconnect PARTIAL
                # candle as closed (missing the trades during the outage),
                # and nothing downstream ever corrects it.
                for topic in topics:
                    sym_iv = topic.split(":", 1)[-1]
                    if "_" in sym_iv:
                        self._last_candle.pop(
                            tuple(sym_iv.rsplit("_", 1)), None
                        )
                WS_RECONNECTS.labels(exchange="kucoin").inc()
                self._health.note_reconnect("kucoin", idx, backoff)
                get_event_log().emit(
                    "ws_reconnect",
                    exchange="kucoin",
                    client=idx,
                    error=str(e),
                    backoff_s=backoff,
                )
                logging.warning(
                    "kucoin ws client %d dropped (%s); reconnecting in %.0fs",
                    idx,
                    e,
                    backoff,
                )
                await asyncio.sleep(
                    reconnect_delay(backoff, rng, self._reconnect_jitter)
                )
                backoff = min(backoff * 2, self._max_backoff_s)

    async def start_stream(self) -> None:
        chunks = self._chunks()
        if not chunks:
            raise WebSocketError("no kucoin topics to subscribe")
        for idx, topics in enumerate(chunks):
            self._tasks.append(
                asyncio.create_task(self._run_client(idx, topics))
            )

    async def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()


class WebsocketClientFactory:
    """Chooses the exchange connector from autotrade settings
    (websocket_factory.py:21-158). Both engine intervals are subscribed
    regardless of exchange — the dual 5m/15m buffers each need live frames.
    """

    def __init__(
        self,
        queue: asyncio.Queue,
        symbols: list[SymbolModel],
        exchange_id: str = "binance",
        market_type: str = "futures",
        connect: Callable[..., Any] | None = None,
        token_fetch: Callable[[], tuple[str, str, float]] | None = None,
    ) -> None:
        self.queue = queue
        self.symbols = symbols
        self.exchange_id = exchange_id
        self.market_type = market_type
        self._connect = connect
        self._token_fetch = token_fetch

    def create_connector(self) -> KlinesConnector | KucoinKlinesConnector:
        if self.exchange_id.lower().startswith("kucoin"):
            return KucoinKlinesConnector(
                self.queue,
                self.symbols,
                market_type=self.market_type,
                intervals=tuple(
                    KUCOIN_WS_INTERVALS[k] for k in ("5m", "15m")
                ),
                connect=self._connect,
                token_fetch=self._token_fetch,
            )
        return KlinesConnector(
            self.queue,
            self.symbols,
            intervals=("5m", "15m"),
            connect=self._connect,
        )
