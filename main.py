"""Entrypoint: one asyncio event loop driving ingest + the TPU tick engine.

Equivalent of ``/root/reference/main.py``: websocket ingest and the consumer
loop joined by an asyncio.Queue, heartbeat per processed tick, per-message
crash isolation. The evaluation itself runs on device via
``binquant_tpu.engine.step.tick_step`` instead of per-symbol pandas.

Replay mode (``--replay file.jsonl``) feeds recorded klines through the
same pipeline with network sinks stubbed — the offline correctness/bench
harness (BASELINE.json config #2).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys


def configure_logging(level: str = "INFO") -> None:
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )


async def run_live() -> None:
    from binquant_tpu.config import Config
    from binquant_tpu.io.autotrade import AutotradeConsumer
    from binquant_tpu.io.binbot import BinbotApi
    from binquant_tpu.io.exchanges import KucoinFutures
    from binquant_tpu.io.pipeline import SignalEngine
    from binquant_tpu.io.telegram import TelegramConsumer
    from binquant_tpu.io.websocket import WebsocketClientFactory

    config = Config()
    configure_logging(config.log_level)
    if config.event_log:
        from binquant_tpu.obs.events import EventLog, set_event_log

        set_event_log(EventLog(config.event_log))
    # bounded REST calls (ISSUE 13 satellite): per-request deadline plus
    # capped, jittered in-client retries; exhaustion is counted
    # (bqt_binbot_retries_total) instead of hanging or crash-ringing
    binbot_api = BinbotApi(
        config.binbot_api_url,
        timeout_s=config.binbot_timeout_s,
        retry_max=config.binbot_retry_max,
        retry_backoff_s=config.binbot_retry_backoff_s,
    )

    autotrade_settings = binbot_api.get_autotrade_settings()
    test_settings = binbot_api.get_test_autotrade_settings()
    all_symbols = binbot_api.get_symbols()
    telegram_consumer = TelegramConsumer(
        token=config.telegram_bot_token, chat_id=config.telegram_user_id
    )
    at_consumer = AutotradeConsumer(
        autotrade_settings=autotrade_settings,
        active_test_bots=binbot_api.get_active_pairs("paper_trading"),
        all_symbols=all_symbols,
        test_autotrade_settings=test_settings,
        active_grid_ladders=binbot_api.get_active_grid_ladders(),
        binbot_api=binbot_api,
    )
    exchange_id = str(autotrade_settings.exchange_id)
    market_type = str(
        getattr(
            autotrade_settings.market_type, "value", autotrade_settings.market_type
        )
    )
    is_kucoin = exchange_id.lower().startswith("kucoin")
    is_futures = market_type.lower().endswith("futures")
    # benchmark symbol per market (klines_provider.py:86-87): the KuCoin
    # futures universe has no BTCUSDT row — the XBTUSDTM contract is BTC
    btc_symbol = "XBTUSDTM" if (is_kucoin and is_futures) else "BTCUSDT"

    futures_api = KucoinFutures()
    engine = SignalEngine(
        config=config,
        binbot_api=binbot_api,
        telegram_consumer=telegram_consumer,
        at_consumer=at_consumer,
        futures_api=futures_api,
        window=config.window_bars,
        btc_symbol=btc_symbol,
        # live loop runs pipelined: dispatch tick i, emit tick i-1 whose
        # wire landed during the idle second — the production shape the
        # p99 < 50 ms budget is measured against
        pipeline_depth=config.pipeline_depth,
    )

    # Resume from the last snapshot if one exists — restores the device
    # buffers, RegimeCarry (incl. regime_stable_since: no 30-minute
    # stability cold-start, unlike the reference's rebuild-on-restart at
    # market_regime/regime_routing.py:41-44), and host dedupe carries.
    from binquant_tpu.io.checkpoint import CheckpointManager

    if config.checkpoint_path:
        engine.checkpoint = CheckpointManager(
            config.checkpoint_path, every_ticks=config.checkpoint_every_ticks
        )
        engine.checkpoint.try_restore(engine)

    # Seed both interval buffers with REST history so strategies can fire
    # on the first live tick (klines_provider.py:278-293) instead of being
    # blind for MIN_BARS * 15m after a cold start. This always runs, even
    # after a checkpoint restore: bars that closed while the process was
    # down never arrive over the websocket, and a gapped window corrupts
    # rolling indicators — the scatter-by-timestamp update is idempotent
    # for bars the snapshot already holds, so topping up is safe.
    from binquant_tpu.io.exchanges import (
        BinanceApi,
        KucoinApi,
        make_history_fetcher,
    )
    from binquant_tpu.io.websocket import (
        filter_fiat_symbols,
        kucoin_futures_ids,
        kucoin_spot_api_symbol,
    )

    fiat_filtered = filter_fiat_symbols(all_symbols)
    if is_kucoin and is_futures:
        # same universe + client the websocket subscription uses
        tracked = kucoin_futures_ids(fiat_filtered)
        history_api = futures_api
        api_symbol_of = None
    elif is_kucoin:
        # engine tracks undashed ids; KuCoin spot REST wants BASE-QUOTE
        dash = {s.id: kucoin_spot_api_symbol(s) for s in fiat_filtered}
        tracked = [s.id for s in fiat_filtered]
        history_api = KucoinApi()
        api_symbol_of = lambda sym: dash.get(sym, sym)  # noqa: E731
    else:
        tracked = [s.id for s in fiat_filtered]
        history_api = BinanceApi()
        api_symbol_of = None

    # A restored snapshot can hold symbols that have since left the
    # universe; reconcile before backfill so stale rows can't accumulate
    # across restarts until registry.add exhausts capacity.
    engine.prune_symbols(tracked + [btc_symbol])

    # Start streaming BEFORE the (multi-minute, serial-REST) backfill:
    # bars that close mid-backfill buffer in the queue — otherwise a
    # symbol fetched before a bar boundary permanently misses that bar
    # (the websocket only delivers bars closing after subscription).
    # The scatter-by-timestamp update dedupes the overlap.
    queue: asyncio.Queue = asyncio.Queue()
    factory = WebsocketClientFactory(
        queue,
        all_symbols,
        exchange_id=exchange_id,
        market_type=market_type,
    )
    connector = factory.create_connector()
    await connector.start_stream()

    await asyncio.to_thread(
        engine.backfill,
        tracked,
        make_history_fetcher(
            history_api,
            exchange_id,
            market_type=market_type,
            api_symbol_of=api_symbol_of,
        ),
    )
    # On-demand jax.profiler capture windows: /debug/profile?seconds=N on
    # the exporter below, and SIGUSR2 for a default 10 s window when the
    # exporter is disabled or unreachable (output under BQT_PROFILE_DIR).
    import signal as signal_module

    from binquant_tpu.obs.tracing import ProfileController

    profile_controller = ProfileController(log_dir=config.profile_dir)
    sigusr2 = getattr(signal_module, "SIGUSR2", None)
    if sigusr2 is not None:
        try:
            asyncio.get_running_loop().add_signal_handler(
                sigusr2, lambda: profile_controller.start_window(10.0)
            )
        except (NotImplementedError, RuntimeError):
            pass  # platform without loop signal handlers

    # Observability exporter: /metrics (Prometheus text) + /healthz
    # (heartbeat age + last-tick status), enabled by BQT_METRICS_PORT.
    metrics_server = None
    if config.metrics_port:
        from binquant_tpu.obs.exposition import MetricsServer
        from binquant_tpu.obs.ledger import LEDGER

        metrics_server = MetricsServer(
            health_fn=lambda: engine.health_snapshot(config.heartbeat_max_age_s),
            port=config.metrics_port,
            profiler=profile_controller,
            # /debug/profile is side-effectful: loopback-only unless the
            # deploy explicitly opens it to the network
            profile_remote_ok=config.profile_remote_ok,
            # /debug/executables: the engine's compile/cost ledger
            # (read-only, served like /metrics)
            ledger=LEDGER,
            # /debug/symbols: the ingest monitor's worst-first per-symbol
            # stream-health scoreboard (read-only, served like /metrics)
            ingest=engine.ingest_monitor,
            # /debug/slo: the unified SLO verdict plane (ISSUE 16;
            # read-only, served like /metrics)
            slo=engine.slo,
        )
        await metrics_server.start()

    # Subscription fan-out broadcast tier (ISSUE 14): serve the WS/SSE
    # hub when BQT_FANOUT_PORT is set (requires BQT_FANOUT=1, the
    # default). Subscribers connect to /ws?user=<id> or /sse?user=<id>
    # (+ an optional cursor) and receive exactly the frames the device
    # match kernel addressed to them; see README §Fan-out plane.
    if engine.fanout is not None and config.fanout_port:
        port = await engine.fanout.serve(
            config.fanout_port, host=config.fanout_host
        )
        logging.info("fanout hub serving ws/sse on port %d", port)

    logging.info("binquant_tpu started: %d symbols tracked", len(all_symbols))
    # OI refresh rides a background task (bounded-concurrency REST sweeps
    # amortized across the bucket); the tick path only reads its cache
    try:
        await asyncio.gather(
            engine.consume_loop(queue),
            engine.oi_cache.refresh_forever(lambda: engine.registry.names),
        )
    finally:
        if metrics_server is not None:
            await metrics_server.stop()


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--replay", help="JSONL kline file for offline replay")
    parser.add_argument("--replay-report", action="store_true")
    parser.add_argument(
        "--backend",
        choices=("tpu", "reference", "ab"),
        default="tpu",
        help="replay evaluation backend: the TPU batch path, the legacy "
        "per-symbol pandas oracle, or an A/B diff of both (BASELINE #1)",
    )
    parser.add_argument(
        "--scanned",
        action="store_true",
        help="drive the TPU replay arm through fused lax.scan chunks "
        "(ISSUE 5): runs of clean-append incremental ticks cost one "
        "dispatch per BQT_SCAN_CHUNK ticks; the emitted signal set is "
        "identical to the serial drive",
    )
    parser.add_argument(
        "--scenario",
        help="run the adversarial scenario engine (ISSUE 10): a scenario "
        "name from binquant_tpu/sim, 'all' for the whole corpus + the "
        "ws/sink chaos drill, or 'list'. Each scenario is driven scanned "
        "AND serial with signal-set equality and the graceful-degradation "
        "invariants asserted; verdicts also land in the event log "
        "(BQT_EVENT_LOG) for tools/scenario_report.py",
    )
    parser.add_argument(
        "--backtest",
        action="store_true",
        help="drive the replay through the time-batched backtest backend "
        "(ISSUE 6): FULL-recompute semantics over (S, W+T) extended "
        "buffers, one dispatch per BQT_BACKTEST_CHUNK ticks; the emitted "
        "signal set is identical to the serial full-recompute drive",
    )
    args = parser.parse_args()

    if args.scenario:
        if args.replay or args.scanned or args.backtest or args.backend != "tpu":
            parser.error(
                "--scenario runs the sim corpus on its own drives (serial "
                "+ scanned + full-oracle); combining it with --replay/"
                "--backend/--scanned/--backtest would be silently ignored"
            )
        from binquant_tpu.sim.runner import main_cli

        return main_cli(args.scenario)
    if args.backend != "tpu" and not args.replay:
        parser.error("--backend reference/ab requires --replay")
    if args.scanned and not args.replay:
        parser.error("--scanned requires --replay")
    if args.backtest and (not args.replay or args.scanned):
        parser.error("--backtest requires --replay and excludes --scanned")
    if args.backtest and args.backend != "tpu":
        parser.error(
            "--backtest drives the TPU backend only (it would be silently "
            "ignored with --backend reference/ab)"
        )

    if args.replay:
        if args.backend == "reference":
            from binquant_tpu.io.replay import run_replay_oracle

            signals = run_replay_oracle(args.replay)
            print({"backend": "reference", "signals": len(signals)})
            return 0
        if args.backend == "ab":
            from binquant_tpu.io.replay import run_replay_ab

            result = run_replay_ab(args.replay, scanned=args.scanned)
            print(result)
            return 0 if result["match"] else 1
        if args.backtest:
            from binquant_tpu.backtest import run_backtest

            print(run_backtest(args.replay))
            return 0
        from binquant_tpu.io.replay import run_replay

        stats = run_replay(args.replay, scanned=args.scanned)
        print(stats)
        return 0

    asyncio.run(run_live())
    return 0


if __name__ == "__main__":
    sys.exit(main())
