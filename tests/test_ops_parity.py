"""Numerical parity of JAX kernels vs a pandas oracle.

This is the correctness gate SURVEY.md §7 prescribes: the reference is
explicit that indicator-variant drift silently shifts strategy thresholds
(``/root/reference/strategies/mean_reversion_fade.py:44-49``), so every
kernel is pinned against the exact pandas expression the reference uses.
"""

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from binquant_tpu.ops import indicators as ind
from binquant_tpu.ops import rolling as roll

ATOL = 2e-3
RTOL = 2e-4


def assert_close(jax_out, pandas_out, atol=ATOL, rtol=RTOL, tail_only=None):
    a = np.asarray(jax_out, dtype=np.float64)
    b = np.asarray(pandas_out, dtype=np.float64)
    if tail_only:
        a, b = a[-tail_only:], b[-tail_only:]
    mask_a, mask_b = np.isfinite(a), np.isfinite(b)
    np.testing.assert_array_equal(mask_a, mask_b, err_msg="NaN mask mismatch")
    np.testing.assert_allclose(a[mask_a], b[mask_b], atol=atol, rtol=rtol)


@pytest.fixture
def series(ohlcv):
    return {k: pd.Series(v) for k, v in ohlcv.items()}


class TestRolling:
    def test_shift(self, ohlcv):
        x = jnp.asarray(ohlcv["close"])
        assert_close(roll.shift(x, 3), pd.Series(ohlcv["close"]).shift(3))
        assert_close(roll.shift(x, -2), pd.Series(ohlcv["close"]).shift(-2))

    @pytest.mark.parametrize("window,mp", [(20, None), (14, 1), (96, 48)])
    def test_rolling_mean(self, ohlcv, window, mp):
        x = jnp.asarray(ohlcv["close"])
        expected = pd.Series(ohlcv["close"]).rolling(window, min_periods=mp).mean()
        assert_close(roll.rolling_mean(x, window, mp), expected)

    def test_rolling_mean_with_leading_nan(self, ohlcv):
        c = ohlcv["close"].copy()
        c[:37] = np.nan
        expected = pd.Series(c).rolling(20, min_periods=1).mean()
        assert_close(roll.rolling_mean(jnp.asarray(c), 20, 1), expected)

    @pytest.mark.parametrize("ddof", [0, 1])
    def test_rolling_std(self, ohlcv, ddof):
        x = jnp.asarray(ohlcv["close"])
        expected = pd.Series(ohlcv["close"]).rolling(20).std(ddof=ddof)
        assert_close(roll.rolling_std(x, 20, ddof=ddof), expected)

    def test_rolling_std_large_prices(self, rng):
        # float32 stability at BTC-scale magnitudes
        c = 68_000.0 + np.cumsum(rng.normal(0, 30, size=400))
        expected = pd.Series(c).rolling(20).std(ddof=0)
        assert_close(roll.rolling_std(jnp.asarray(c), 20, ddof=0), expected, atol=0.5, rtol=1e-3)

    def test_rolling_max_min(self, ohlcv):
        x = jnp.asarray(ohlcv["high"])
        assert_close(roll.rolling_max(x, 48), pd.Series(ohlcv["high"]).rolling(48).max())
        assert_close(roll.rolling_min(x, 48), pd.Series(ohlcv["high"]).rolling(48).min())

    @pytest.mark.parametrize("q", [0.5, 0.8, 0.92])
    def test_rolling_quantile(self, ohlcv, q):
        x = jnp.asarray(ohlcv["volume"])
        expected = pd.Series(ohlcv["volume"]).rolling(48).quantile(q)
        assert_close(roll.rolling_quantile(x, 48, q), expected)

    @pytest.mark.parametrize("num_out", [1, 4, 9])
    def test_rolling_quantile_tail_matches_full(self, ohlcv, num_out):
        x = jnp.asarray(ohlcv["volume"])
        full = np.asarray(roll.rolling_quantile(x, 48, 0.92, min_periods=20))
        tail = np.asarray(
            roll.rolling_quantile_tail(x, 48, 0.92, num_out=num_out, min_periods=20)
        )
        np.testing.assert_allclose(tail, full[-num_out:], rtol=1e-6, equal_nan=True)

    def test_rolling_quantile_tail_short_series_warmup(self):
        # series shorter than window+num_out-1: leading windows truncated
        x = jnp.asarray(np.arange(10.0))
        full = np.asarray(roll.rolling_quantile(x, 8, 0.5, min_periods=3))
        tail = np.asarray(
            roll.rolling_quantile_tail(x, 8, 0.5, num_out=6, min_periods=3)
        )
        np.testing.assert_allclose(tail, full[-6:], rtol=1e-6, equal_nan=True)

    def test_rolling_median_shifted(self, ohlcv):
        # shifted rolling median — the activity_burst_pump baseline pattern
        x = roll.shift(jnp.asarray(ohlcv["volume"]), 1)
        expected = pd.Series(ohlcv["volume"]).shift(1).rolling(24).median()
        assert_close(roll.rolling_median(x, 24), expected)

    @pytest.mark.parametrize("span", [7, 20, 26, 50, 100])
    def test_ewm_span(self, ohlcv, span):
        x = jnp.asarray(ohlcv["close"])
        expected = pd.Series(ohlcv["close"]).ewm(span=span, adjust=False, min_periods=1).mean()
        assert_close(roll.ewm_mean(x, span=span, min_periods=1), expected)

    def test_ewm_alpha_with_min_periods(self, ohlcv):
        x = jnp.asarray(ohlcv["close"])
        expected = (
            pd.Series(ohlcv["close"]).ewm(alpha=1 / 14, adjust=False, min_periods=14).mean()
        )
        assert_close(roll.ewm_mean(x, alpha=1 / 14, min_periods=14), expected)

    def test_ewm_with_leading_nan(self, ohlcv):
        c = ohlcv["close"].copy()
        c[:53] = np.nan
        expected = pd.Series(c).ewm(span=20, adjust=False, min_periods=1).mean()
        assert_close(roll.ewm_mean(jnp.asarray(c), span=20, min_periods=1), expected)

    def test_batched_matches_single(self, rng):
        xs = np.stack([rng.normal(100, 5, 200) for _ in range(8)])
        batched = roll.rolling_mean(jnp.asarray(xs), 20)
        for i in range(8):
            single = roll.rolling_mean(jnp.asarray(xs[i]), 20)
            np.testing.assert_allclose(
                np.asarray(batched[i]), np.asarray(single), atol=1e-5, equal_nan=True
            )


class TestIndicators:
    def test_rsi_wilder(self, series, ohlcv):
        # exact expression from the reference backtest kernel
        closes = series["close"]
        delta = closes.diff()
        gain = delta.clip(lower=0)
        loss = -delta.clip(upper=0)
        avg_gain = gain.ewm(alpha=1 / 14, min_periods=14, adjust=False).mean()
        avg_loss = loss.ewm(alpha=1 / 14, min_periods=14, adjust=False).mean()
        denom = avg_gain + avg_loss
        expected = (100 * avg_gain / denom).where(denom != 0, 50.0)
        assert_close(ind.rsi_wilder(jnp.asarray(ohlcv["close"]), 14), expected, atol=0.05)

    def test_rsi_sma(self, series, ohlcv):
        closes = series["close"]
        delta = closes.diff()
        gain = delta.clip(lower=0).rolling(14).mean()
        loss = (-delta.clip(upper=0)).rolling(14).mean()
        denom = gain + loss
        expected = (100 * gain / denom).where(denom != 0, 50.0)
        assert_close(ind.rsi_sma(jnp.asarray(ohlcv["close"]), 14), expected, atol=0.05)

    def test_true_range_and_atr(self, series, ohlcv):
        h, low, c = series["high"], series["low"], series["close"]
        prev = c.shift(1)
        tr = pd.concat([h - low, (h - prev).abs(), (low - prev).abs()], axis=1).max(axis=1)
        expected_atr = tr.rolling(14, min_periods=1).mean()
        got = ind.atr(
            jnp.asarray(ohlcv["high"]), jnp.asarray(ohlcv["low"]), jnp.asarray(ohlcv["close"]),
            14, min_periods=1,
        )
        assert_close(got, expected_atr)

    def test_macd(self, series, ohlcv):
        c = series["close"]
        line = (
            c.ewm(span=12, adjust=False).mean() - c.ewm(span=26, adjust=False).mean()
        )
        sig = line.ewm(span=9, adjust=False).mean()
        got = ind.macd(jnp.asarray(ohlcv["close"]))
        assert_close(got.macd, line, atol=5e-3)
        assert_close(got.signal, sig, atol=5e-3)

    def test_bollinger(self, series, ohlcv):
        c = series["close"]
        mid = c.rolling(20, min_periods=1).mean()
        std = c.rolling(20, min_periods=1).std(ddof=0).fillna(0.0)
        got = ind.bollinger(jnp.asarray(ohlcv["close"]), 20, 2.0, min_periods=1)
        assert_close(got.upper, mid + 2 * std)
        assert_close(got.lower, mid - 2 * std)

    def test_mfi_bounds_and_direction(self, ohlcv):
        got = np.asarray(
            ind.mfi(
                jnp.asarray(ohlcv["high"]),
                jnp.asarray(ohlcv["low"]),
                jnp.asarray(ohlcv["close"]),
                jnp.asarray(ohlcv["volume"]),
            )
        )
        valid = got[np.isfinite(got)]
        assert valid.size > 350
        assert np.all(valid >= 0) and np.all(valid <= 100)

    def test_zscore(self, series, ohlcv):
        c = series["close"]
        mu = c.rolling(20).mean()
        sd = c.rolling(20).std(ddof=0)
        expected = (c - mu) / sd
        assert_close(ind.zscore(jnp.asarray(ohlcv["close"]), 20), expected, atol=5e-3)

    def test_rolling_beta_corr(self, rng):
        bench = rng.normal(0, 0.01, 300)
        asset = 1.5 * bench + rng.normal(0, 0.005, 300)
        sb, sa = pd.Series(bench), pd.Series(asset)
        expected_corr = sa.rolling(50).corr(sb)
        expected_beta = sa.rolling(50).cov(sb, ddof=0) / sb.rolling(50).var(ddof=0)
        got = ind.rolling_beta_corr(jnp.asarray(asset), jnp.asarray(bench), 50)
        assert_close(got.corr, expected_corr, atol=5e-3)
        assert_close(got.beta, expected_beta, atol=5e-3)

    def test_adx_in_bounds(self, ohlcv):
        got = np.asarray(
            ind.adx(jnp.asarray(ohlcv["high"]), jnp.asarray(ohlcv["low"]), jnp.asarray(ohlcv["close"]))
        )
        valid = got[np.isfinite(got)]
        assert valid.size > 300
        assert np.all(valid >= 0) and np.all(valid <= 100)

    def test_supertrend_flips_with_trend(self, rng):
        up = 100 * np.exp(np.cumsum(np.full(150, 0.01)))
        down = up[-1] * np.exp(np.cumsum(np.full(150, -0.01)))
        c = np.concatenate([up, down])
        h, low = c * 1.002, c * 0.998
        got = ind.supertrend(jnp.asarray(h), jnp.asarray(low), jnp.asarray(c))
        d = np.asarray(got.direction)
        assert d[140] == 1.0
        assert d[-1] == -1.0

    def test_connors_rsi_extremes(self):
        # monotonic rally then crash → CRSI should sit near the extremes
        up = 100 * np.exp(np.cumsum(np.full(200, 0.004)))
        c = np.concatenate([up, up[-1] * np.exp(np.cumsum(np.full(10, -0.02)))])
        got = np.asarray(ind.connors_rsi(jnp.asarray(c)))
        assert got[195] > 60
        assert got[-1] < 25


class TestLastValueKernels:
    """ewm_mean_last / rolling_*_last must equal the full kernel's last column."""

    @pytest.mark.parametrize("span", [20, 50])
    def test_ewm_mean_last(self, ohlcv, span):
        x = jnp.asarray(ohlcv["close"])
        full = roll.ewm_mean(x, span=span, min_periods=1)
        last = roll.ewm_mean_last(x, span=span, min_periods=1)
        np.testing.assert_allclose(
            float(last), float(full[-1]), rtol=1e-5, atol=1e-4
        )
        expected = pd.Series(ohlcv["close"]).ewm(span=span, adjust=False, min_periods=1).mean().iloc[-1]
        np.testing.assert_allclose(float(last), expected, rtol=1e-4)

    def test_ewm_mean_last_leading_nan(self, ohlcv):
        c = ohlcv["close"].copy()
        c[:123] = np.nan
        last = roll.ewm_mean_last(jnp.asarray(c), span=20, min_periods=1)
        expected = pd.Series(c).ewm(span=20, adjust=False, min_periods=1).mean().iloc[-1]
        np.testing.assert_allclose(float(last), expected, rtol=1e-4)

    def test_ewm_mean_last_batched(self, rng):
        x = rng.normal(100, 5, size=(7, 64))
        x[2, :30] = np.nan
        x[5, :] = np.nan
        last = np.asarray(roll.ewm_mean_last(jnp.asarray(x), span=20, min_periods=1))
        for i in range(7):
            exp = pd.Series(x[i]).ewm(span=20, adjust=False, min_periods=1).mean().iloc[-1]
            if np.isnan(exp):
                assert np.isnan(last[i])
            else:
                np.testing.assert_allclose(last[i], exp, rtol=1e-4)

    @pytest.mark.parametrize("window,mp", [(20, None), (14, 1)])
    def test_rolling_mean_last(self, ohlcv, window, mp):
        x = jnp.asarray(ohlcv["close"])
        expected = pd.Series(ohlcv["close"]).rolling(window, min_periods=mp).mean().iloc[-1]
        np.testing.assert_allclose(
            float(roll.rolling_mean_last(x, window, mp)), expected, rtol=1e-5
        )

    @pytest.mark.parametrize("ddof", [0, 1])
    def test_rolling_std_last(self, ohlcv, ddof):
        x = jnp.asarray(ohlcv["close"])
        expected = pd.Series(ohlcv["close"]).rolling(20).std(ddof=ddof).iloc[-1]
        np.testing.assert_allclose(
            float(roll.rolling_std_last(x, 20, ddof=ddof)), expected, rtol=1e-4
        )

    def test_rolling_last_short_history(self):
        x = jnp.asarray(np.concatenate([np.full(15, np.nan), [1.0, 2.0, 3.0]]))
        np.testing.assert_allclose(
            float(roll.rolling_mean_last(x, 14, 1)), 2.0, rtol=1e-6
        )
        assert np.isnan(float(roll.rolling_mean_last(x, 14, None)))


def test_supertrend_matches_pandas():
    """Full-series numeric parity of the scan-based supertrend against an
    independent sequential pandas/python mirror (the same recursion the
    refdiff shim ships): Wilder-ATR ewm seeding, min_periods gating, band
    ratchet, flip state, and the start-offset variant."""
    import numpy as np
    import pandas as pd

    from binquant_tpu.ops.indicators import supertrend, supertrend_from

    rng = np.random.default_rng(421)
    W = 160
    close = 100 * np.exp(np.cumsum(rng.normal(0, 0.01, W)))
    spread = np.abs(rng.normal(0, 0.004, W)) * close
    high, low = close + spread, close - spread

    def pandas_mirror(h, lo, c, period=10, mult=3.0):
        h, lo, c = pd.Series(h), pd.Series(lo), pd.Series(c)
        pc = c.shift(1)
        tr = pd.concat([h - lo, (h - pc).abs(), (lo - pc).abs()], axis=1).max(axis=1)
        tr = tr.where(pc.notna(), h - lo)
        atr = tr.ewm(alpha=1.0 / period, adjust=False, min_periods=period).mean()
        hl2 = (h + lo) / 2.0
        upper = (hl2 + mult * atr).to_numpy()
        lower = (hl2 - mult * atr).to_numpy()
        cs = c.to_numpy()
        n = len(cs)
        dirn = np.full(n, np.nan)
        line = np.full(n, np.nan)
        fu, fl, d, prev = np.inf, -np.inf, 1.0, 0.0
        for i in range(n):
            ub = upper[i] if np.isfinite(upper[i]) else np.inf
            lb = lower[i] if np.isfinite(lower[i]) else -np.inf
            fu = ub if (ub < fu or prev > fu) else fu
            fl = lb if (lb > fl or prev < fl) else fl
            d = 1.0 if cs[i] > fu else (-1.0 if cs[i] < fl else d)
            if np.isfinite(atr.iloc[i]):
                dirn[i] = d
                line[i] = fl if d > 0 else fu
            prev = cs[i]
        return line, dirn

    exp_line, exp_dir = pandas_mirror(high, low, close)
    got = supertrend(high[None, :], low[None, :], close[None, :])
    np.testing.assert_allclose(
        np.asarray(got.supertrend)[0], exp_line, rtol=1e-5, equal_nan=True
    )
    np.testing.assert_allclose(np.asarray(got.direction)[0], exp_dir, equal_nan=True)

    # start-offset variant == plain variant on the sliced series
    start = 37
    exp_line_s, exp_dir_s = pandas_mirror(high[start:], low[start:], close[start:])
    got_s = supertrend_from(
        high[None, :], low[None, :], close[None, :], np.array([start])
    )
    np.testing.assert_allclose(
        np.asarray(got_s.supertrend)[0, start:], exp_line_s, rtol=1e-5, equal_nan=True
    )
    np.testing.assert_allclose(
        np.asarray(got_s.direction)[0, start:], exp_dir_s, equal_nan=True
    )
    # a mid-series NaN bar poisons the recursion: NaN from the gap onward,
    # never frozen stale values
    high2, low2, close2 = high.copy(), low.copy(), close.copy()
    high2[80] = np.nan
    got_gap = supertrend(high2[None, :], low2[None, :], close2[None, :])
    assert np.isnan(np.asarray(got_gap.direction)[0, 80:]).all()


# ---------------------------------------------------------------------------
# Incremental carries (ops/incremental.py): init_from_window + one-bar
# advance must track the full-window kernels over random update streams,
# including NaN warm-up, mid-stream NaN gaps, and rewrite-triggered
# re-initialization (ISSUE 2 tentpole parity gate).
# ---------------------------------------------------------------------------


class TestIncrementalOps:
    W = 256  # sliding-window length: long enough that EWM window
    # forgetting ((1-a)^W) is far below the assertion tolerances

    def _stream(self, rng, n, scale=100.0, vol=0.01, nan_gaps=()):
        x = scale * np.exp(np.cumsum(rng.normal(0, vol, n)))
        x[:17] = np.nan  # warm-up
        for g in nan_gaps:
            x[g] = np.nan
        return x

    def _window(self, x, t):
        lo = t + 1 - self.W
        if lo >= 0:
            return x[lo : t + 1]
        return np.concatenate([np.full(-lo, np.nan), x[: t + 1]])

    @pytest.mark.parametrize("alpha", [2.0 / 10, 1.0 / 14, 2.0 / 27])
    def test_ewm_advance_tracks_full_window(self, rng, alpha):
        from binquant_tpu.ops import incremental as inc

        x = self._stream(rng, self.W + 80, nan_gaps=(40, 41, 200))
        carry = inc.ewm_init(jnp.asarray(self._window(x, self.W - 1)), alpha)
        for t in range(self.W, len(x)):
            carry = inc.ewm_advance(carry, jnp.asarray(x[t]), alpha)
            full = roll.ewm_mean_last(
                jnp.asarray(self._window(x, t)), alpha=alpha, min_periods=14
            )
            np.testing.assert_allclose(
                np.asarray(inc.ewm_value(carry, 14)),
                np.asarray(full),
                rtol=2e-4,
                atol=2e-3,
                equal_nan=True,
            )

    def test_sum_and_mean_advance(self, rng):
        from binquant_tpu.ops import incremental as inc

        window = 14
        x = self._stream(rng, self.W + 80, nan_gaps=(300,))
        carry = inc.sum_init(jnp.asarray(self._window(x, self.W - 1)), window)
        for t in range(self.W, len(x)):
            leaver = self._window(x, t)[-(window + 1)]
            carry = inc.sum_advance(carry, jnp.asarray(x[t]), jnp.asarray(leaver))
            full = roll.rolling_mean_last(jnp.asarray(self._window(x, t)), window)
            np.testing.assert_allclose(
                np.asarray(inc.sum_mean(carry, window)),
                np.asarray(full),
                rtol=1e-5,
                atol=1e-4,
                equal_nan=True,
            )

    @pytest.mark.parametrize("scale", [100.0, 68_000.0])
    def test_moment_advance_mean_std(self, rng, scale):
        """Centered sum-of-squares stays f32-exact even at BTC-scale
        prices (the uncentered form loses ~8% of a 20-bar variance)."""
        from binquant_tpu.ops import incremental as inc

        window = 20
        x = self._stream(rng, self.W + 100, scale=scale, vol=0.004, nan_gaps=(290,))
        carry = inc.moment_init(jnp.asarray(self._window(x, self.W - 1)), window)
        for t in range(self.W, len(x)):
            leaver = self._window(x, t)[-(window + 1)]
            carry = inc.moment_advance(carry, jnp.asarray(x[t]), jnp.asarray(leaver))
            win = jnp.asarray(self._window(x, t))
            np.testing.assert_allclose(
                np.asarray(inc.moment_mean(carry, window)),
                np.asarray(roll.rolling_mean_last(win, window)),
                rtol=1e-5,
                atol=scale * 1e-5,
                equal_nan=True,
            )
            np.testing.assert_allclose(
                np.asarray(inc.moment_std(carry, window, ddof=0)),
                np.asarray(roll.rolling_std_last(win, window, ddof=0)),
                rtol=5e-3,
                atol=scale * 1e-5,
                equal_nan=True,
            )

    def test_rewrite_requires_reinit_and_reinit_matches(self, rng):
        """A mid-window rewrite invalidates carried sums; re-init from the
        rewritten window (what the engine's full-recompute fallback does)
        restores exact parity on the same tick AND on subsequent advances."""
        from binquant_tpu.ops import incremental as inc

        window = 14
        x = self._stream(rng, self.W + 40)
        carry = inc.sum_init(jnp.asarray(self._window(x, self.W - 1)), window)
        for t in range(self.W, self.W + 10):
            carry = inc.sum_advance(
                carry, jnp.asarray(x[t]), jnp.asarray(self._window(x, t)[-(window + 1)])
            )
        t = self.W + 9
        x[t - 5] *= 1.5  # exchange re-sent a corrected mid-window candle
        full = roll.rolling_mean_last(jnp.asarray(self._window(x, t)), window)
        stale = inc.sum_mean(carry, window)
        assert not np.allclose(np.asarray(stale), np.asarray(full))
        carry = inc.sum_init(jnp.asarray(self._window(x, t)), window)  # resync
        for t in range(self.W + 10, len(x)):
            carry = inc.sum_advance(
                carry, jnp.asarray(x[t]), jnp.asarray(self._window(x, t)[-(window + 1)])
            )
            np.testing.assert_allclose(
                np.asarray(inc.sum_mean(carry, window)),
                np.asarray(
                    roll.rolling_mean_last(jnp.asarray(self._window(x, t)), window)
                ),
                rtol=1e-5,
                atol=1e-4,
                equal_nan=True,
            )

    def test_supertrend_advance_extends_scan(self, rng):
        """advance == extending the path-dependent scan by exactly one bar
        (the contract that makes the carry a drop-in for the recursion)."""
        from binquant_tpu.ops import incremental as inc

        n = 140
        close = 100 * np.exp(np.cumsum(rng.normal(0.001, 0.01, (3, n)), axis=1))
        spread = np.abs(rng.normal(0, 0.004, (3, n))) * close
        high, low = close + spread, close - spread
        high[1, :9] = np.nan
        low[1, :9] = np.nan
        close[1, :9] = np.nan
        H, L, C = jnp.asarray(high), jnp.asarray(low), jnp.asarray(close)
        # the scan is causal, so one full-series run supplies the expected
        # value at EVERY prefix length (per-prefix scans would jit-compile
        # a fresh program per t)
        full = ind.supertrend(H, L, C)
        full_line = np.asarray(full.supertrend)
        full_dir = np.asarray(full.direction)
        carry = inc.supertrend_init(H[:, :60], L[:, :60], C[:, :60])
        for t in range(60, n):
            carry, line, dirn = inc.supertrend_advance(
                carry, H[:, t], L[:, t], C[:, t]
            )
            np.testing.assert_allclose(
                np.asarray(line), full_line[:, t], rtol=1e-5, equal_nan=True
            )
            np.testing.assert_allclose(
                np.asarray(dirn), full_dir[:, t], equal_nan=True
            )

    @pytest.mark.slow
    @pytest.mark.parametrize("q,mp", [(0.5, 19), (0.92, 20)])
    def test_sorted_window_tracks_pandas_quantiles(self, rng, q, mp):
        """SortedCarry advance == pandas rolling().median()/.quantile(q,
        'linear') over a stream with NaN warm-up + mid-stream gaps and
        min_periods edges (the ABP baseline and threshold configurations;
        LSP's cnt>0 edge rides the strategy twin test). Slow lane +
        ``make strat-smoke``, with the other sorted-window props (tier-1
        budget — the 870s lane keeps tests/test_cost_budget.py as the
        ISSUE-4 gate; the per-bar pandas sweeps opt in)."""
        import pandas as pd

        from binquant_tpu.ops import incremental as inc

        window = 19 if q == 0.5 else 48
        x = self._stream(rng, self.W + 64, nan_gaps=(280, 281, 300))
        ref = (
            pd.Series(np.asarray(x, np.float64))
            .rolling(window, min_periods=mp)
            .quantile(q, interpolation="linear")
            .to_numpy()
        )
        carry = inc.sorted_init(jnp.asarray(self._window(x, self.W - 1)), window)
        for t in range(self.W, len(x)):
            leaver = self._window(x, t)[-(window + 1)]
            carry = inc.sorted_advance(carry, jnp.asarray(x[t]), jnp.asarray(leaver))
            got = np.asarray(inc.sorted_quantile(carry, q, min_periods=mp))
            np.testing.assert_allclose(
                got, ref[t], rtol=1e-5, atol=1e-4, equal_nan=True,
                err_msg=f"t={t}",
            )

    @pytest.mark.slow
    def test_sorted_window_eviction_order_with_duplicates(self, rng):
        """Duplicate values: each advance must evict exactly ONE instance
        of the leaving value — the carried multiset stays equal to a fresh
        sort of the trailing window (bit-for-bit, so readouts match the
        full path's windowed sort exactly)."""
        from binquant_tpu.ops import incremental as inc

        window = 8
        # heavy duplication: values drawn from 4 distinct levels
        x = rng.choice([1.0, 2.0, 2.0, 3.0, 7.0], size=120).astype(np.float32)
        x[[30, 31, 60]] = np.nan
        carry = inc.sorted_init(jnp.asarray(x[:40]), window)
        for t in range(40, len(x)):
            carry = inc.sorted_advance(
                carry, jnp.asarray(x[t]), jnp.asarray(x[t - window])
            )
            ref = inc.sorted_init(jnp.asarray(x[: t + 1]), window)
            np.testing.assert_array_equal(
                np.asarray(carry.sorted), np.asarray(ref.sorted), err_msg=f"t={t}"
            )
            assert int(carry.cnt) == int(ref.cnt)

    @pytest.mark.slow
    def test_sorted_window_reinit_resync(self, rng):
        """A mid-window rewrite desyncs the carried multiset; re-init from
        the rewritten series (the engine's full-recompute resync) restores
        bit parity on the same tick and on subsequent advances."""
        from binquant_tpu.ops import incremental as inc

        window = 19
        x = self._stream(rng, self.W + 40)
        carry = inc.sorted_init(jnp.asarray(x[: self.W]), window)
        for t in range(self.W, self.W + 10):
            carry = inc.sorted_advance(
                carry, jnp.asarray(x[t]), jnp.asarray(x[t - window])
            )
        t = self.W + 9
        x[t - 5] *= 1.5  # corrected mid-window candle
        ref = inc.sorted_init(jnp.asarray(x[: t + 1]), window)
        assert not np.array_equal(np.asarray(carry.sorted), np.asarray(ref.sorted))
        carry = ref  # resync
        for t in range(self.W + 10, len(x)):
            carry = inc.sorted_advance(
                carry, jnp.asarray(x[t]), jnp.asarray(x[t - window])
            )
            ref = inc.sorted_init(jnp.asarray(x[: t + 1]), window)
            np.testing.assert_array_equal(
                np.asarray(carry.sorted), np.asarray(ref.sorted)
            )

    def test_beta_corr_advance(self, rng):
        from binquant_tpu.ops import incremental as inc

        window = 50
        n = self.W + 60
        x = rng.normal(0, 0.01, (3, n))
        y = rng.normal(0, 0.01, n)
        x[2, 310] = np.nan  # asymmetric gap: pair masking must hold
        X, Y = jnp.asarray(x), jnp.asarray(y)
        carry = inc.beta_corr_init(X[:, : self.W], Y[None, : self.W], window)
        for t in range(self.W, n):
            carry = inc.beta_corr_advance(
                carry, X[:, t], Y[t], X[:, t - window], Y[t - window]
            )
            full = ind.rolling_beta_corr(
                X[:, t - self.W + 1 : t + 1], Y[None, t - self.W + 1 : t + 1], window
            )
            beta, corr = inc.beta_corr_value(carry, window)
            np.testing.assert_allclose(
                np.asarray(beta),
                np.asarray(full.beta[:, -1]),
                rtol=1e-3,
                atol=1e-3,
                equal_nan=True,
            )
            np.testing.assert_allclose(
                np.asarray(corr),
                np.asarray(full.corr[:, -1]),
                rtol=1e-3,
                atol=1e-3,
                equal_nan=True,
            )
