"""MarketRegimeNotifier — Telegram digest on regime transitions (host).

Equivalent of ``/root/reference/strategies/market_regime_notifier.py``: a
scalar-per-tick concern (one market, one message), so it stays host-side.
Emits a structured digest on each *new* market regime transition, deduped by
remembering the last transition sent (reference ``last_market_regime``,
l.42-53).
"""

from __future__ import annotations

import numpy as np

from binquant_tpu.enums import MarketRegimeCode, MarketTransitionCode
from binquant_tpu.regime.context import MarketContext


def _regime_summary(regime: int) -> str:
    if regime == MarketRegimeCode.TREND_UP:
        return "market conditions now favor long continuation"
    if regime == MarketRegimeCode.TREND_DOWN:
        return "market conditions now favor downside continuation"
    if regime == MarketRegimeCode.HIGH_STRESS:
        return "market conditions have shifted into a stressed risk-off state"
    if regime == MarketRegimeCode.RANGE:
        return "market conditions now favor mean-reversion and range trading"
    return "market conditions are mixed, transitional, or range-bound"


class MarketRegimeNotifier:
    def __init__(self, env: str = "") -> None:
        self.env = env
        self.last_transition_sent: int | None = None

    def build_message(self, context: MarketContext) -> str | None:
        """Digest text for a new transition, or None when nothing to send."""
        if not bool(np.asarray(context.valid)):
            return None
        transition = int(np.asarray(context.market_regime_transition))
        previous = int(np.asarray(context.previous_market_regime))
        current = int(np.asarray(context.market_regime))
        if transition < 0 or previous < 0 or current < 0:
            return None
        if transition == self.last_transition_sent:
            return None
        self.last_transition_sent = transition

        r3 = lambda v: round(float(np.asarray(v)), 3)
        prev_name = MarketRegimeCode(previous).name
        cur_name = MarketRegimeCode(current).name
        transition_name = MarketTransitionCode(transition).name
        ts = int(np.asarray(context.timestamp)) * 1000
        return f"""
            - [{self.env}] <strong>#market_regime_transition</strong>
            - Event: {transition_name}
            - Regime transition: {prev_name} -> {cur_name}
            - Market regime: {cur_name}
            - Market transition: {transition_name}
            - Interpretation: {_regime_summary(current)}
            - Context timestamp: {ts}
            - Confidence: 1.0
            - Transition strength: {r3(context.market_regime_transition_strength)}
            - Fresh symbols: {int(np.asarray(context.fresh_count))}
            - Advancers ratio: {r3(context.advancers_ratio)}
            - Long regime score: {r3(context.long_regime_score)}
            - Short regime score: {r3(context.short_regime_score)}
            - Range regime score: {r3(context.range_regime_score)}
            - Stress regime score: {r3(context.stress_regime_score)}
            - Avg return: {round(float(np.asarray(context.average_return)), 4)}
            - BTC regime score: {r3(context.btc_regime_score)}
            - Long tailwind: {r3(context.long_tailwind)}
            - Short tailwind: {r3(context.short_tailwind)}
            - Market stress: {r3(context.market_stress_score)}
        """
