"""End-to-end latency observatory: freshness stamps + host-phase dwell.

Two host-side instruments answering the two questions the per-stage
histograms (io/metrics.py) and tick traces (obs/tracing.py) cannot:

* :class:`FreshnessTracker` — **how stale is a signal when it reaches a
  sink?** Every tick carries its evaluated candle-close time and its
  oldest pending candle's ingest-arrival monotonic stamp; finalize turns
  them into ``bqt_freshness_ms{stage}`` observations (close→dispatch,
  ingest→dispatch, dispatch→wire-fetch, close→emit, close→sink-ack) plus
  per-sink delivery histograms. A configurable SLO
  (``BQT_FRESHNESS_SLO_MS``) force-emits a ``freshness_slo_breach``
  event — flight-recorder style, with the host-phase breakdown of the
  producing chunk and an engine snapshot — whenever a signal's worst
  close→sink-ack exceeds it. Mixed clocks by design: the ``close_to_*``
  stages are *logical* (measured against the tick's own clock, exact
  live where tick time ≈ wall clock, deterministic in replay), the
  ``ingest_to_dispatch``/``dispatch_to_*`` stages are real
  ``perf_counter`` deltas.

* :class:`PhaseAccountant` — **where do a drive's milliseconds go?** One
  phase taxonomy shared by every backend (:data:`PHASES` — plan, stack,
  dispatch, device_wait, decode, emit), recorded per drive (serial /
  scanned / backtest) into ``bqt_host_phase_ms{drive,phase}``, plus a
  per-chunk occupancy split: device-wait vs host-busy vs the dead gap
  neither accounts for, cumulative per drive and as
  ``bqt_chunk_occupancy_ratio`` gauges. ``device_wait`` brackets the
  blocking wire fetch, so on an asynchronously-dispatching backend it is
  a *lower bound* on device busy time (work overlapping host phases is
  invisible to a host clock); the dead gap is the residual the chunk's
  wall clock holds against every named bracket — the acceptance target
  is ≥ 90% of chunk wall attributed (dead gap ≤ 10%).

Both default ON in production and OFF in the tier-1 test lane
(``BQT_FRESHNESS`` / ``BQT_HOST_PHASE`` — the ``BQT_TRACE_SAMPLE``
pattern). Disabled instances are allocation-free no-ops on the hot path,
and nothing here touches the device wire: the no-observatory sink
payloads and event records are byte-identical (the freshness fields are
only stamped when enabled).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Callable

from binquant_tpu.obs.events import get_event_log
from binquant_tpu.obs.instruments import (
    CHUNK_OCCUPANCY,
    FRESHNESS,
    FRESHNESS_SLO_BREACHES,
    HOST_PHASE,
    SINK_DELIVERY,
)

#: The one phase taxonomy every drive reports (tests pin serial ==
#: scanned == backtest): plan (drain/route/per-tick planning), stack
#: (update packing + HostInputs build), dispatch (the jit launch),
#: device_wait (blocking wire fetch), decode (wire→FiredSignal,
#: dedupe, policy refresh), emit (sink dispatch).
PHASES = ("plan", "stack", "dispatch", "device_wait", "decode", "emit")

#: Freshness stages exported under bqt_freshness_ms{stage}.
FRESHNESS_STAGES = (
    "close_to_dispatch",
    "ingest_to_dispatch",
    "dispatch_to_fetch",
    "close_to_emit",
    "close_to_sink_ack",
)


class FreshnessTracker:
    """Candle-close→sink-ack freshness accounting for one engine."""

    def __init__(
        self, enabled: bool = True, slo_ms: float = 0.0, slo=None
    ) -> None:
        self.enabled = bool(enabled)
        # 0 disables the breach check (stamps still record when enabled)
        self.slo_ms = max(float(slo_ms), 0.0)
        # the unified SloRegistry (ISSUE 16): the PR 11 freshness SLO
        # re-homed — every observation also feeds the "freshness" SLO's
        # burn/recover model; the breach event below keeps firing
        # untouched
        self.slo = slo
        self.signals = 0
        self.breaches = 0
        # last observed value per stage (healthz introspection)
        self.last: dict[str, float] = {}

    def observe_stage(self, stage: str, ms: float) -> None:
        if not self.enabled:
            return
        ms = float(ms)
        FRESHNESS.labels(stage=stage).observe(ms)
        self.last[stage] = round(ms, 3)

    def observe_signal(
        self,
        strategy: str,
        symbol: str,
        close_to_emit_ms: float,
        sink_ack_ms: dict[str, float] | None = None,
        tick_ms: int | None = None,
        trace_id: str | None = None,
        phases: dict | None = None,
        snapshot_fn: Callable[[], dict] | None = None,
    ) -> float | None:
        """One emitted signal's freshness: records close→emit, per-sink
        delivery, and close→sink-ack (the worst sink); runs the SLO check.
        ``phases`` is the producing chunk's host-phase breakdown — a
        breach event must say where the milliseconds went, not just that
        they were spent. ``snapshot_fn`` is only called on a breach."""
        if not self.enabled:
            return None
        self.signals += 1
        self.observe_stage("close_to_emit", close_to_emit_ms)
        worst = float(close_to_emit_ms)
        for sink, ms in (sink_ack_ms or {}).items():
            ms = float(ms)
            SINK_DELIVERY.labels(sink=sink).observe(ms)
            worst = max(worst, ms)
        self.observe_stage("close_to_sink_ack", worst)
        breached = self.slo_ms > 0 and worst >= self.slo_ms
        if self.slo is not None and self.slo_ms > 0:
            self.slo.observe(
                "freshness",
                ok=not breached,
                worst_ms=round(worst, 3),
                strategy=strategy,
                symbol=symbol,
            )
        if breached:
            self.breaches += 1
            FRESHNESS_SLO_BREACHES.inc()
            get_event_log().emit(
                "freshness_slo_breach",
                strategy=strategy,
                symbol=symbol,
                close_to_sink_ack_ms=round(worst, 3),
                close_to_emit_ms=round(float(close_to_emit_ms), 3),
                slo_ms=self.slo_ms,
                sink_ack_ms={
                    k: round(float(v), 3)
                    for k, v in (sink_ack_ms or {}).items()
                },
                tick_ms=tick_ms,
                trace_id=trace_id,
                host_phases=phases or {},
                engine=snapshot_fn() if snapshot_fn is not None else {},
            )
        return worst

    def snapshot(self) -> dict:
        return {
            "enabled": self.enabled,
            "slo_ms": self.slo_ms,
            "signals": self.signals,
            "slo_breaches": self.breaches,
            "last_ms": dict(self.last),
        }


class PhaseAccountant:
    """Per-drive host-phase dwell totals + per-chunk occupancy splits."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = bool(enabled)
        # drive -> phase -> [total_ms, count]
        self.totals: dict[str, dict[str, list]] = {}
        # drive -> cumulative occupancy tallies
        self.occupancy: dict[str, dict[str, float]] = {}
        # the newest chunk's full split (flight recorder / breach events)
        self.last_chunk: dict | None = None
        # drive -> marks at the OPEN chunk's start (begin_chunk); lets a
        # mid-chunk reader (an SLO breach fired during finalize) report
        # the PRODUCING chunk's split-so-far instead of the previous one
        self._open: dict[str, dict[str, float]] = {}

    def record(self, drive: str, phase: str, ms: float) -> None:
        if not self.enabled:
            return
        ms = float(ms)
        slot = self.totals.setdefault(drive, {}).setdefault(phase, [0.0, 0])
        slot[0] += ms
        slot[1] += 1
        HOST_PHASE.labels(drive=drive, phase=phase).observe(ms)

    @contextmanager
    def phase(self, drive: str, phase: str):
        """Time a block into ``record`` — free when disabled."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(drive, phase, (time.perf_counter() - t0) * 1000.0)

    def marks(self, drive: str) -> dict[str, float]:
        """Per-phase cumulative-ms snapshot — ``note_chunk`` diffs against
        it so a chunk's split only covers its own brackets."""
        return {p: s[0] for p, s in self.totals.get(drive, {}).items()}

    def begin_chunk(self, drive: str) -> None:
        """Open a chunk: snapshot the marks ``note_chunk`` will diff
        against, and make ``open_split`` report this chunk's phases."""
        if self.enabled:
            self._open[drive] = self.marks(drive)

    def _split_since(self, drive: str, marks: dict[str, float]) -> dict:
        now = self.marks(drive)
        phases = {
            p: round(now.get(p, 0.0) - marks.get(p, 0.0), 3)
            for p in set(now) | set(marks)
        }
        return {p: v for p, v in phases.items() if v}

    def open_split(self, drive: str) -> dict | None:
        """The OPEN chunk's per-phase dwell so far (``drive`` + phase
        deltas since ``begin_chunk``) — what an SLO breach fired mid-chunk
        attaches; None when no chunk is open (or disabled)."""
        marks = self._open.get(drive)
        if not self.enabled or marks is None:
            return None
        return {"drive": drive, **self._split_since(drive, marks)}

    def note_chunk(
        self, drive: str, wall_ms: float, ticks: int
    ) -> dict | None:
        """Close the open chunk's occupancy accounting: phase deltas since
        ``begin_chunk``, device-wait vs host-busy vs dead-gap against the
        chunk's wall clock (the serial drive calls this per tick)."""
        if not self.enabled:
            return None
        phases = self._split_since(drive, self._open.pop(drive, {}))
        device = phases.get("device_wait", 0.0)
        host = sum(v for p, v in phases.items() if p != "device_wait")
        dead = max(float(wall_ms) - device - host, 0.0)
        occ = {
            "drive": drive,
            "wall_ms": round(float(wall_ms), 3),
            "ticks": int(ticks),
            "device_wait_ms": round(device, 3),
            "host_ms": round(host, 3),
            "dead_gap_ms": round(dead, 3),
            "attributed_pct": (
                round(100.0 * (device + host) / wall_ms, 1)
                if wall_ms > 0
                else None
            ),
            "phases": phases,
        }
        self.last_chunk = occ
        agg = self.occupancy.setdefault(
            drive,
            {
                "wall_ms": 0.0,
                "device_wait_ms": 0.0,
                "host_ms": 0.0,
                "dead_gap_ms": 0.0,
                "chunks": 0,
                "ticks": 0,
            },
        )
        agg["wall_ms"] += float(wall_ms)
        agg["device_wait_ms"] += device
        agg["host_ms"] += host
        agg["dead_gap_ms"] += dead
        agg["chunks"] += 1
        agg["ticks"] += int(ticks)
        if wall_ms > 0:
            for component, value in (
                ("device_wait", device),
                ("host", host),
                ("dead_gap", dead),
            ):
                CHUNK_OCCUPANCY.labels(drive=drive, component=component).set(
                    round(value / wall_ms, 4)
                )
        return occ

    def reset(self) -> None:
        """Drop totals (benches reuse one engine across warmup/measure;
        the global histogram mirror is cumulative by design)."""
        self.totals.clear()
        self.occupancy.clear()
        self.last_chunk = None
        self._open.clear()

    def snapshot(self) -> dict:
        phase_ms: dict[str, dict[str, Any]] = {}
        for drive, by_phase in self.totals.items():
            phase_ms[drive] = {
                p: {"total_ms": round(s[0], 3), "count": s[1]}
                for p, s in by_phase.items()
            }
        occupancy: dict[str, dict[str, Any]] = {}
        for drive, agg in self.occupancy.items():
            wall = agg["wall_ms"]
            occupancy[drive] = {
                "wall_ms": round(wall, 3),
                "device_wait_ms": round(agg["device_wait_ms"], 3),
                "host_ms": round(agg["host_ms"], 3),
                "dead_gap_ms": round(agg["dead_gap_ms"], 3),
                "chunks": int(agg["chunks"]),
                "ticks": int(agg["ticks"]),
                "attributed_pct": (
                    round(
                        100.0
                        * (agg["device_wait_ms"] + agg["host_ms"])
                        / wall,
                        1,
                    )
                    if wall > 0
                    else None
                ),
            }
        return {
            "enabled": self.enabled,
            "phase_ms": phase_ms,
            "occupancy": occupancy,
            "last_chunk": self.last_chunk,
        }
