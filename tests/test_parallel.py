"""Multi-chip sharding tests: sharded tick_step == unsharded tick_step.

SURVEY §2.9: the framework's parallelism is data parallelism over the
symbol axis (NamedSharding over a 1-D ``symbols`` mesh). These tests pin
that the sharded step produces bit-for-bit (float-tolerant) identical
outputs and that the driver-facing ``dryrun_multichip`` entry succeeds.

On plain hosts/CI the conftest provisions an 8-device virtual CPU mesh
in-process. On the tunneled-TPU host the axon sitecustomize forces the
1-chip TPU backend, so the in-process tests skip and the subprocess
tests (which set the escape-hatch env before jax import) carry the
coverage.
"""

import subprocess
import sys

import jax
import pytest

import __graft_entry__ as graft

multi = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh)"
)


@multi
def test_sharded_tick_matches_unsharded():
    graft._parity_check(8)


@multi
def test_dryrun_multichip_inprocess():
    graft._dryrun_inprocess(8)


def test_mesh_shardings_place_symbol_axis():
    from binquant_tpu.parallel import make_mesh, shard_engine_state

    n = min(len(jax.devices()), 8)
    mesh = make_mesh(jax.devices()[:n])
    state, _, _ = graft._example_inputs(num_symbols=n * 2, window=64)
    sharded = shard_engine_state(state, mesh)
    spec = sharded.buf15.values.sharding.spec
    assert spec[0] == "symbols"
    # carry scalars replicated
    assert sharded.regime_carry.market_regime.sharding.is_fully_replicated


def test_dryrun_multichip_driver_entry():
    """The driver calls dryrun_multichip(n) in-process with whatever
    backend is active; it must succeed regardless (subprocess fallback)."""
    graft.dryrun_multichip(8)


@multi
def test_collective_audit_no_buffer_gather():
    """Compiled-HLO proof of the mesh.py:5-9 claim: the (S,W,F) buffers
    are never all-gathered; every collective is orders of magnitude
    smaller than a buffer leaf (VERDICT r3 item 4)."""
    graft._collective_audit(8, num_symbols=256, window=400)


_T0 = 1_753_000_200


def _ingest_bars(engine, symbols, price: float = 1.0, bars: int = 3):
    """Feed `bars` closed 15m candles per symbol (the batcher's expected
    ExtendedKline key set) and run one tick + flush."""
    import asyncio

    for sym in symbols:
        for b in range(bars):
            ts = _T0 + b * 900
            engine.ingest(
                {
                    "symbol": sym,
                    "open_time": ts * 1000,
                    "close_time": (ts + 900) * 1000 - 1,
                    "open": price, "high": price * 1.01,
                    "low": price * 0.99, "close": price,
                    "volume": 10.0,
                    "quote_asset_volume": 10.0 * price,
                    "number_of_trades": 5,
                }
            )
    asyncio.run(engine.process_tick(now_ms=(_T0 + bars * 900) * 1000))
    asyncio.run(engine.flush_pending())


@multi
def test_signal_engine_mesh_mode_shards_state(monkeypatch):
    """BQT_MESH_DEVICES wires the mesh into the production SignalEngine:
    carried state is placed on the symbols mesh at startup and STAYS
    sharded after a real process_tick."""
    from binquant_tpu.io.replay import make_stub_engine

    monkeypatch.setenv("BQT_MESH_DEVICES", "8")
    engine = make_stub_engine(capacity=32, window=120)
    assert engine.mesh is not None
    assert engine.state.buf15.values.sharding.spec[0] == "symbols"

    _ingest_bars(engine, [f"S{i:03d}USDT" for i in range(8)])
    # the carried state must still be sharded over the mesh after a tick
    assert engine.state.buf15.values.sharding.spec[0] == "symbols"
    # and the candles actually landed (8 symbols x 3 bars)
    import numpy as np

    assert int((np.asarray(engine.state.buf15.times) >= 0).sum()) == 24


@multi
def test_mesh_checkpoint_restore_reshards(tmp_path, monkeypatch):
    """A checkpoint written by a mesh-mode engine restores into a fresh
    mesh-mode engine SHARDED (checkpoint.py re-places restored leaves on
    the mesh) with every state leaf and the host carries intact."""
    import jax
    import numpy as np

    from binquant_tpu.io.checkpoint import CheckpointManager
    from binquant_tpu.io.replay import make_stub_engine

    monkeypatch.setenv("BQT_MESH_DEVICES", "8")
    a = make_stub_engine(capacity=32, window=120)
    _ingest_bars(a, [f"M{i:03d}USDT" for i in range(8)], price=2.0)
    ckpt = CheckpointManager(tmp_path / "mesh.npz", every_ticks=1)
    assert ckpt.maybe_save(a)

    b = make_stub_engine(capacity=32, window=120)
    b.checkpoint = CheckpointManager(tmp_path / "mesh.npz", every_ticks=1)
    assert b.checkpoint.try_restore(b)

    assert b.mesh is not None
    assert b.state.buf15.values.sharding.spec[0] == "symbols"
    # EVERY state leaf round-trips (times, OHLCV values, fills, carries)
    for (path, la), lb in zip(
        jax.tree_util.tree_leaves_with_path(a.state),
        jax.tree_util.tree_leaves(b.state),
    ):
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb),
            err_msg=jax.tree_util.keystr(path),
        )
    assert b.ticks_processed == a.ticks_processed
    assert b._last_emitted == a._last_emitted


@pytest.mark.slow
def test_parity_subprocess_eight_cpu_devices():
    """Full sharded-vs-unsharded parity under a forced 8-CPU mesh, env set
    before jax import (works on the tunneled-TPU host too)."""
    proc = subprocess.run(
        [sys.executable, "-c", "import __graft_entry__ as g; g._parity_check(8)"],
        env=graft._subprocess_env(8),
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "parity ok" in proc.stdout


def test_make_mesh_rejects_multiprocess(monkeypatch):
    """The mesh path is single-host by construction (shard_host_inputs
    device_puts full host arrays); a pod must fail fast at mesh creation,
    not mid-tick inside device_put."""
    import jax

    from binquant_tpu.parallel import make_mesh

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(NotImplementedError, match="single-host"):
        make_mesh()
