"""Multi-chip scaling: shard the symbol axis over a device mesh.

The reference's only parallelism is asyncio concurrency + websocket
connection sharding (SURVEY.md §2.9); the TPU-native analogue is data
parallelism over symbols: every (S, ...) array in the engine state shards
along S over a 1-D ``symbols`` mesh, XLA inserts the few collectives the
market-context aggregates need (masked sums → psum over ICI), and everything
else stays embarrassingly parallel.
"""

from binquant_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    shard_engine_state,
    shard_host_inputs,
    symbol_sharding,
)
