"""Leverage calibrator.

Equivalent of ``/root/reference/calibrators/leverage_calibrator.py``: per
15-minute bucket, map the regime to a per-symbol futures leverage ladder
(expensive/defensive/stressed/low-confidence/spiky → 1x; RANGE → 2x; trends
→ 3x) and PUT via ``edit_symbol`` only on change. Consumes a host snapshot
of the device context (numpy'd ``MarketContext``) plus the symbol registry.
"""

from __future__ import annotations

import logging
from typing import NamedTuple

import numpy as np

from binquant_tpu.engine.buffer import SymbolRegistry
from binquant_tpu.enums import MarketRegimeCode
from binquant_tpu.io.binbot import BinbotApi
from binquant_tpu.regime.context import MarketContext
from binquant_tpu.schemas import SymbolModel


class CalibrationInputs(NamedTuple):
    """Host snapshot of the calibrator's per-symbol inputs, decoded from
    the tick wire (engine/step.py calib_block) — zero device fetches."""

    valid: np.ndarray  # (S,) bool
    close: np.ndarray  # (S,) f32
    atr_pct: np.ndarray  # (S,) f32
    regime: int
    stress: float
    confidence: float


class LeverageCalibrator:
    MAX_LEVERAGE = 3
    DEFAULT_PRICE_HIGH_THRESHOLD = 500.0
    DEFAULT_STRESS_THRESHOLD = 0.7
    DEFAULT_CONFIDENCE_FLOOR = 0.5
    DEFAULT_ATR_HIGH_THRESHOLD = 0.04

    def __init__(
        self,
        binbot_api: BinbotApi,
        exchange: str,
        *,
        price_high_threshold: float = DEFAULT_PRICE_HIGH_THRESHOLD,
        stress_threshold: float = DEFAULT_STRESS_THRESHOLD,
        confidence_floor: float = DEFAULT_CONFIDENCE_FLOOR,
        atr_high_threshold: float = DEFAULT_ATR_HIGH_THRESHOLD,
    ) -> None:
        self.binbot_api = binbot_api
        self.exchange = exchange
        self.price_high_threshold = price_high_threshold
        self.stress_threshold = stress_threshold
        self.confidence_floor = confidence_floor
        self.atr_high_threshold = atr_high_threshold

    def _regime_defensive(self, regime: int) -> bool:
        return regime in (
            int(MarketRegimeCode.HIGH_STRESS),
            int(MarketRegimeCode.TRANSITIONAL),
        )

    def target_leverage(
        self, close: float, atr_pct: float | None, regime: int, stress: float,
        confidence: float,
    ) -> int:
        """Decision ladder (reference l.50-79)."""
        if close >= self.price_high_threshold:
            return 1
        if self._regime_defensive(regime):
            return 1
        if stress > self.stress_threshold:
            return 1
        if confidence < self.confidence_floor:
            return 1
        if atr_pct is not None and atr_pct > self.atr_high_threshold:
            return 1
        if regime == int(MarketRegimeCode.RANGE):
            return 2
        if regime in (int(MarketRegimeCode.TREND_UP), int(MarketRegimeCode.TREND_DOWN)):
            return self.MAX_LEVERAGE
        return 1

    def target_leverage_batch(
        self,
        closes: np.ndarray,
        atr_pcts: np.ndarray,
        regime: int,
        stress: float,
        confidence: float,
    ) -> np.ndarray:
        """Vectorized decision ladder — one pass over all rows instead of a
        per-row Python walk (the per-bucket diff at S=4096 was a visible
        tick-thread spike in the accelerated bench). NaN ``atr_pct`` means
        "unavailable" and, like the scalar ladder's ``None``, does not cap
        (NaN > threshold is False)."""
        if (
            self._regime_defensive(regime)
            or stress > self.stress_threshold
            or confidence < self.confidence_floor
        ):
            regime_leverage = 1
        elif regime == int(MarketRegimeCode.RANGE):
            regime_leverage = 2
        elif regime in (
            int(MarketRegimeCode.TREND_UP),
            int(MarketRegimeCode.TREND_DOWN),
        ):
            regime_leverage = self.MAX_LEVERAGE
        else:
            regime_leverage = 1
        capped = (closes >= self.price_high_threshold) | (
            atr_pcts > self.atr_high_threshold
        )
        return np.where(capped, 1, regime_leverage).astype(np.int64)

    @staticmethod
    def _row_models(
        registry: SymbolRegistry | object, all_symbols: list[SymbolModel]
    ) -> tuple[np.ndarray, list[SymbolModel]]:
        """(rows, models) pairs resolved in ONE pass over the symbol list
        instead of a per-valid-row name_of + rows_by_id walk every bucket —
        O(len(all_symbols)) Python, zero when the list is empty (replay /
        bench engines). Accepts a live :class:`SymbolRegistry` or the
        engine's FrozenRows snapshot (both expose the row↔name mapping)."""
        if not all_symbols:
            return np.empty(0, np.int64), []
        if hasattr(registry, "row_of"):
            lookup = registry.row_of
        else:  # FrozenRows
            mapping = {
                name: row
                for row, name in registry._row_to_name.items()  # type: ignore[attr-defined]
            }
            lookup = mapping.get
        rows: list[int] = []
        models: list[SymbolModel] = []
        for row_model in all_symbols:
            row = lookup(row_model.id)
            if row is not None and int(row) >= 0:
                rows.append(int(row))
                models.append(row_model)
        return np.asarray(rows, np.int64), models

    def calibrate_all(
        self,
        context: MarketContext | CalibrationInputs,
        registry: SymbolRegistry,
        all_symbols: list[SymbolModel],
    ) -> dict[str, int]:
        """Diff-and-PUT for every feature-valid row (reference l.81-127).

        Accepts either a wire-decoded :class:`CalibrationInputs` snapshot
        (the production path — no device fetches) or a raw
        ``MarketContext`` (tests / direct use — fetched here). Safe to run
        off the tick thread against a :class:`FrozenRows` snapshot — the
        engine schedules it as a background worker so a bucket-boundary
        tick costs the same as any other.

        The diff itself is vectorized: targets come from
        :meth:`target_leverage_batch` and the no-change verdict from one
        numpy comparison, so the Python loop below walks only rows whose
        leverage actually CHANGES (the PUTs). Replay/bench engines with an
        empty symbol list — every bucket on compressed clocks — now cost
        ~zero instead of an O(S) per-row walk stealing a core from the
        tick thread."""
        applied = no_change = skipped = 0

        if isinstance(context, CalibrationInputs):
            valid = context.valid
            closes = context.close
            atr_pcts = context.atr_pct
            regime = context.regime
            stress = context.stress
            confidence = context.confidence
        else:
            valid = np.asarray(context.features.valid)
            closes = np.asarray(context.features.close)
            atr_pcts = np.asarray(context.features.atr_pct)
            regime = int(np.asarray(context.market_regime))
            stress = float(np.asarray(context.market_stress_score))
            confidence = 1.0 if bool(np.asarray(context.valid)) else 0.0

        targets = self.target_leverage_batch(
            np.asarray(closes, np.float64),
            np.asarray(atr_pcts, np.float64),
            int(regime),
            float(stress),
            float(confidence),
        )
        valid = np.asarray(valid, bool)
        model_rows, model_refs = self._row_models(registry, all_symbols)
        in_range = model_rows < valid.shape[0]
        model_rows = model_rows[in_range]
        model_refs = [m for m, ok in zip(model_refs, in_range) if ok]
        model_of: dict[int, SymbolModel] = dict(zip(model_rows.tolist(), model_refs))
        covered = np.zeros(valid.shape, bool)
        covered[model_rows] = True
        # float dtype: SymbolModel.futures_leverage is a float field — an
        # int array would truncate 2.5 -> 2 and misreport it as no_change
        # against an integer target, skipping the correcting PUT forever
        current = np.full(valid.shape, -1.0, np.float64)
        if len(model_rows):
            current[model_rows] = [m.futures_leverage for m in model_refs]
        skipped += int(np.count_nonzero(valid & ~covered))
        no_change += int(np.count_nonzero(valid & covered & (targets == current)))
        # only genuinely-changing rows reach Python (the PUT loop) — a
        # steady-state or symbol-less (replay/bench) bucket walks nothing
        for row_idx in np.nonzero(valid & covered & (targets != current))[0]:
            row = model_of[int(row_idx)]
            target = int(targets[row_idx])
            try:
                self.binbot_api.edit_symbol(
                    row.id,
                    exchange_id=self.exchange,
                    futures_leverage=target,
                )
                row.futures_leverage = target
                applied += 1
            except Exception:
                logging.exception(
                    "[LeverageCalibrator] failed to update %s -> %s",
                    row.id,
                    target,
                )
                skipped += 1

        logging.info(
            "[LeverageCalibrator] applied=%d no_change=%d skipped=%d",
            applied,
            no_change,
            skipped,
        )
        return {"applied": applied, "no_change": no_change, "skipped": skipped}
