"""Ingest-health observatory (ISSUE 15): wire ingest digest, host-side
per-symbol monitor, staleness SLO, /debug/symbols, and the report tools.

Tier-1 keeps the small-shape drills: digest layout + bit-identical-when-off
parity (the acceptance pin), device-side batch classification
(append/rewrite/gap/drop), the staleness/coverage reductions, the
cross-backend digest equality pin on a clean stream
(serial == donated == scanned == backtest == classic — the acceptance
criterion), the host monitor units (classification, health score,
pagination, snapshot/rewind, SLO trip/clear), the /debug/symbols route,
and the report goldens. The churn+rewrite stream drill is slow-marked
into ``make ingest-smoke``.
"""

import asyncio
import json

import jax.numpy as jnp
import numpy as np
import pytest

from binquant_tpu.engine.buffer import NUM_FIELDS, Field, SymbolRegistry
from binquant_tpu.engine.step import (
    INGEST_DIGEST_WIDTH,
    _ingest_batch_counts,
    _ingest_interval_stats,
    apply_updates_step,
    decode_ingest_digest,
    default_host_inputs,
    ingest_digest_layout,
    initial_engine_state,
    pad_updates,
    tick_step_wire,
    unpack_wire,
    wire_length,
)
from binquant_tpu.obs.events import EventLog, set_event_log
from binquant_tpu.obs.ingest import IngestHealthMonitor
from tests.conftest import make_ohlcv

S_CAP = 16
WINDOW = 130


@pytest.fixture
def event_log(tmp_path):
    path = tmp_path / "events.jsonl"
    log = EventLog(path)
    set_event_log(log)
    yield path
    log.close()
    set_event_log(None)


def _read_events(path) -> list[dict]:
    if not path.exists():
        return []
    return [json.loads(line) for line in path.read_text().splitlines()]


def _bar_updates(frames: dict[int, dict], bar: int, size: int):
    rows, tss, vals = [], [], []
    for row, d in frames.items():
        v = np.zeros(NUM_FIELDS, dtype=np.float32)
        v[Field.OPEN], v[Field.HIGH] = d["open"][bar], d["high"][bar]
        v[Field.LOW], v[Field.CLOSE] = d["low"][bar], d["close"][bar]
        v[Field.VOLUME] = d["volume"][bar]
        v[Field.QUOTE_VOLUME] = d["quote_asset_volume"][bar]
        v[Field.NUM_TRADES] = 100
        v[Field.DURATION_S] = 900
        rows.append(row)
        tss.append(int(d["open_time"][bar]) // 1000)
        vals.append(v)
    return pad_updates(
        np.array(rows, np.int32), np.array(tss, np.int32), np.stack(vals),
        size=size,
    )


def _seeded_state(n_rows=8, n_bars=WINDOW, seed=3):
    rng = np.random.default_rng(seed)
    frames = {
        i: make_ohlcv(rng, n=n_bars, start_price=30 + i, vol=0.006)
        for i in range(n_rows)
    }
    state = initial_engine_state(S_CAP, window=WINDOW)
    for b in range(n_bars):
        upd = _bar_updates(frames, b, S_CAP)
        state = apply_updates_step(state, upd, upd)
    return state, frames


def _inputs(ts_s: int, n_rows=8):
    tracked = np.zeros(S_CAP, dtype=bool)
    tracked[:n_rows] = True
    return default_host_inputs(S_CAP)._replace(
        tracked=jnp.asarray(tracked),
        btc_row=np.int32(0),
        timestamp_s=np.int32(ts_s),
        timestamp5_s=np.int32(ts_s),
    )


def test_ingest_layout_matches_width():
    layout = ingest_digest_layout()
    assert len(layout) == INGEST_DIGEST_WIDTH
    assert layout[0] == "tracked"
    assert layout[1] == "5m.stale_1x"
    assert len(set(layout)) == len(layout)


def test_wire_bit_identical_with_ingest_off_and_append_only():
    """The acceptance pin: BQT_INGEST_DIGEST=0 compiles the pre-ingest
    wire bit-for-bit, and the enabled block is a strict append after the
    (optional) numeric digest — every earlier offset survives."""
    state, frames = _seeded_state()
    ts = int(frames[0]["open_time"][-1]) // 1000
    upd = _bar_updates(frames, WINDOW - 1, S_CAP)
    inputs = _inputs(ts)

    _, w_default = tick_step_wire(state, upd, upd, inputs)
    _, w_off = tick_step_wire(state, upd, upd, inputs, ingest_digest=False)
    _, w_on = tick_step_wire(state, upd, upd, inputs, ingest_digest=True)
    w_default, w_off, w_on = map(np.asarray, (w_default, w_off, w_on))

    assert w_off.shape == (wire_length(S_CAP),)
    assert np.array_equal(w_default.view(np.int32), w_off.view(np.int32))
    assert w_on.shape == (wire_length(S_CAP, ingest_digest=True),)
    assert np.array_equal(
        w_on[: len(w_off)].view(np.int32), w_off.view(np.int32)
    )
    # both digests stack: numeric first, ingest strictly last
    _, w_both = tick_step_wire(
        state, upd, upd, inputs, numeric_digest=True, ingest_digest=True
    )
    w_both = np.asarray(w_both)
    assert w_both.shape == (
        wire_length(S_CAP, numeric_digest=True, ingest_digest=True),
    )
    _, ctx_both = unpack_wire(w_both, numeric_digest=True, ingest_digest=True)
    assert "numeric_digest" in ctx_both and "ingest_digest" in ctx_both

    # decode: the evaluated batch RE-SENDS each row's already-seeded last
    # bar (same ts as the ring's latest) — exactly a same-bar correction,
    # so the digest classifies all 8 as rewrites, zero appends
    _, ctx = unpack_wire(w_on, ingest_digest=True)
    digest = decode_ingest_digest(ctx["ingest_digest"])
    assert digest["tracked"] == 8
    for interval in ("5m", "15m"):
        sect = digest[interval]
        assert sect["appends"] == 0
        assert sect["rewrites"] == 8
        assert sect["gap_appends"] == sect["dropped"] == 0
        assert sect["covered"] == 8
        assert sect["min_bars"] == 8  # WINDOW=130 seeded bars >= MIN_BARS
        assert sect["fresh"] == 8
        assert sect["stale_1x"] == 0
        assert sect["max_age_s"] == 0.0
    assert digest["stale_total"] == 0
    _, ctx_off = unpack_wire(w_off)
    assert "ingest_digest" not in ctx_off


def test_batch_counts_classify_like_apply_updates():
    """Device classification unit: append / gap append / rewrite (latest
    AND mid-history) / dropped (stale insert with no matching bar), judged
    against the pre-update ring exactly as apply_updates routes them."""
    state, frames = _seeded_state(n_rows=4)
    buf = state.buf15
    last_ts = int(frames[0]["open_time"][-1]) // 1000

    rows = np.array([0, 1, 2, 3], np.int32)
    ts = np.array(
        [
            last_ts + 900,  # clean next-bucket append
            last_ts + 3 * 900,  # append skipping two buckets: gap
            last_ts,  # re-send of the latest bar: rewrite
            last_ts - 900 + 450,  # off-grid old ts, no matching bar: drop
        ],
        np.int32,
    )
    counts = np.asarray(
        _ingest_batch_counts(buf, jnp.asarray(rows), jnp.asarray(ts), 900)
    )
    assert counts.tolist() == [2.0, 1.0, 1.0, 1.0]

    # mid-history rewrite (an old bar that IS in the window) counts as a
    # rewrite, not a drop; out-of-range rows are ignored entirely
    rows2 = np.array([0, 5_000], np.int32)
    ts2 = np.array([last_ts - 10 * 900, last_ts], np.int32)
    counts2 = np.asarray(
        _ingest_batch_counts(buf, jnp.asarray(rows2), jnp.asarray(ts2), 900)
    )
    assert counts2.tolist() == [0.0, 1.0, 0.0, 0.0]

    # an empty (all-padding) batch is an exact zero
    empty = pad_updates(
        np.zeros(0, np.int32), np.zeros(0, np.int32),
        np.zeros((0, NUM_FIELDS), np.float32), size=4,
    )
    counts3 = np.asarray(
        _ingest_batch_counts(buf, jnp.asarray(empty[0]), jnp.asarray(empty[1]), 900)
    )
    assert counts3.tolist() == [0.0, 0.0, 0.0, 0.0]


def test_interval_stats_staleness_buckets():
    """Staleness/coverage reductions: cumulative 1x/3x/10x thresholds over
    tracked rows with data, max age, and the coverage funnel."""
    latest = jnp.asarray(
        np.array([1000, 1000 - 900, 1000 - 2 * 900, 1000 - 4 * 900,
                  1000 - 11 * 900, -1, 1000, 1000], np.int32)
    )
    filled = jnp.asarray(
        np.array([120, 120, 120, 120, 120, 0, 50, 120], np.int32)
    )
    tracked = jnp.asarray(
        np.array([1, 1, 1, 1, 1, 1, 1, 0], bool)
    )
    stats = [
        float(v)
        for v in _ingest_interval_stats(latest, filled, tracked, 1000, 900)
    ]
    stale_1x, stale_3x, stale_10x, max_age, covered, min_bars, fresh = stats
    # ages: 0, 900 (exactly one bucket: NOT stale), 1800, 3600, 9900
    assert stale_1x == 3  # 1800, 3600, 9900 > 900
    assert stale_3x == 2  # 3600, 9900 > 2700
    assert stale_10x == 1  # 9900 > 9000
    assert max_age == 9900.0
    assert covered == 6  # tracked with data (row 7 untracked, row 5 empty)
    assert min_bars == 5  # row 6 has only 50 bars
    assert fresh == 1  # only row 0 is sufficient AND at the eval bucket
    # no tracked data at all → max_age decodes NaN
    none_stats = _ingest_interval_stats(
        latest, filled, jnp.zeros((8,), bool), 1000, 900
    )
    assert np.isnan(float(none_stats[3]))


def _drive(mode, path, **kw):
    from binquant_tpu.io.replay import make_stub_engine, tick_seq

    seq = tick_seq(path)
    eng = make_stub_engine(
        capacity=16, window=112, ingest_digest=True, scan_chunk=8,
        backtest_chunk=8, **kw,
    )
    eng.ingest_monitor.record_history = True

    async def go():
        out = []
        if mode == "scanned":
            out.extend(await eng.process_ticks_scanned(seq))
        elif mode == "backtest":
            out.extend(await eng.process_ticks_backtest(seq))
        else:
            for now_ms, klines in seq:
                for k in klines:
                    eng.ingest(k)
                out.extend(await eng.process_tick(now_ms=now_ms))
        out.extend(await eng.flush_pending())
        return out

    signals = asyncio.run(go())
    return eng, signals


def test_cross_backend_ingest_digest_equality(tmp_path):
    """The acceptance criterion: all four backends (serial, donated,
    scanned, backtest — plus the classic serial path) emit bit-identical
    per-tick ingest digests on a clean stream, fold slots included (every
    15m tick drains three 5m sub-batches here)."""
    from binquant_tpu.io.replay import generate_replay_file

    path = tmp_path / "clean.jsonl"
    generate_replay_file(path, n_symbols=10, n_ticks=20, seed=5)

    engines = {
        "serial": _drive("serial", path, incremental=True)[0],
        "donated": _drive("serial", path, incremental=True, donate=True)[0],
        "scanned": _drive("scanned", path, incremental=True)[0],
        "backtest": _drive("backtest", path, incremental=False)[0],
        "classic": _drive("serial", path, incremental=False)[0],
    }
    mats = {
        k: np.stack(e.ingest_monitor.digests) for k, e in engines.items()
    }
    base = mats["serial"]
    assert base.shape == (20, INGEST_DIGEST_WIDTH)
    for name, mat in mats.items():
        assert mat.shape == base.shape, name
        assert np.array_equal(
            mat.view(np.int32), base.view(np.int32)
        ), f"{name} digest diverged from serial"
    # the batch drives really batched (the equality is cross-executable)
    assert engines["scanned"].scan_chunks > 0
    assert engines["backtest"].backtest_chunks > 0
    assert engines["donated"].donated_ticks > 0
    # fold slots counted: each 15m tick applies three 5m bars per symbol
    last = decode_ingest_digest(base[-1])
    assert last["5m"]["appends"] == 30
    assert last["15m"]["appends"] == 10
    # a clean stream never burns the staleness budget
    assert all(
        e.ingest_monitor.anomaly_ticks == 0 for e in engines.values()
    )


def _mk_monitor(n=4, budget=0):
    reg = SymbolRegistry(8)
    for i in range(n):
        reg.add(f"S{i:03d}USDT")
    return IngestHealthMonitor(reg, enabled=True, stale_budget=budget), reg


def test_monitor_classification_score_and_pagination():
    mon, reg = _mk_monitor()
    t0 = 900_000
    # establish bars on every row
    rows = np.arange(4, dtype=np.int64)
    mon.note_applied_batch(
        "15m", rows, np.full(4, t0, np.int64), np.full(4, -1, np.int64)
    )
    # row 1 gaps (skips 2 buckets), row 2 rewrites, row 3 out-of-order
    mon.note_applied_batch(
        "15m",
        np.array([0, 1, 2, 3], np.int64),
        np.array([t0 + 900, t0 + 3 * 900, t0, t0 - 900], np.int64),
        np.array([t0, t0, t0, t0], np.int64),
    )
    assert mon.appends[0] == 2 and mon.gaps[0] == 0
    assert mon.gaps[1] == 1
    assert mon.rewrites[2] == 1
    assert mon.out_of_order[3] == 1
    # arrival watermark + feed lag
    mon.note_arrival("S000USDT", close_ms=5_000, exchange="kucoin",
                     now_ms=6_500.0)
    assert mon.feed_lag_last_ms["kucoin"] == 1_500.0
    assert mon.arrivals == 1

    # worst-first: the stale rows rank below the fresh frontier row
    report = mon.symbols_report(limit=10)
    assert report["total"] == 4
    scores = [s["score"] for s in report["symbols"]]
    assert scores == sorted(scores)
    worst = report["symbols"][0]
    assert worst["symbol"] in ("S002USDT", "S003USDT")
    # frontier is row 1's t0+3*900; row 0 at t0+900 is 2 buckets behind
    by_name = {s["symbol"]: s for s in report["symbols"]}
    assert by_name["S000USDT"]["age_s"]["15m"] == 2 * 900
    # pagination + prefix filter
    page = mon.symbols_report(offset=1, limit=2)
    assert [s["symbol"] for s in page["symbols"]] == [
        s["symbol"] for s in report["symbols"][1:3]
    ]
    only = mon.symbols_report(prefix="S001")
    assert [s["symbol"] for s in only["symbols"]] == ["S001USDT"]
    # min_score keeps the unhealthy tail only
    tail = mon.symbols_report(min_score=0.5)
    assert all(s["score"] <= 0.5 for s in tail["symbols"])

    # snapshot/rewind: an overflow re-drive must not double-count
    snap = mon.snapshot_state()
    before = int(mon.appends[1])
    mon.note_applied_batch(
        "15m", np.array([1], np.int64),
        np.array([t0 + 4 * 900], np.int64), np.array([t0 + 3 * 900], np.int64),
    )
    assert mon.appends[1] == before + 1
    mon.restore_state(snap)
    assert mon.appends[1] == before


def test_monitor_churn_rehoming_resets_row_stats():
    mon, reg = _mk_monitor(n=2)
    mon.note_applied_batch(
        "15m", np.array([0, 1], np.int64),
        np.full(2, 900_000, np.int64), np.full(2, -1, np.int64),
    )
    assert mon.appends[1] == 1
    # symbol leaves, a newcomer claims its row
    reg.remove("S001USDT")
    reg.add("NEWUSDT")
    mon.note_applied_batch(
        "15m", np.array([1], np.int64),
        np.array([900_900], np.int64), np.array([-1], np.int64),
    )
    assert mon.names[1] == "NEWUSDT"
    assert mon.churn[1] == 1
    assert mon.churn_total == 1
    # the departed symbol's history did not leak onto the newcomer
    assert mon.appends[1] == 1


def _digest_vec(stale5=0, stale15=0, tracked=8, fresh=8):
    layout = ingest_digest_layout()
    vec = np.zeros(len(layout), np.float32)
    vals = {
        "tracked": tracked,
        "5m.stale_1x": stale5, "15m.stale_1x": stale15,
        "5m.covered": tracked, "15m.covered": tracked,
        "5m.min_bars": tracked, "15m.min_bars": tracked,
        "5m.fresh": fresh, "15m.fresh": fresh,
        "5m.appends": tracked, "15m.appends": tracked,
    }
    for key, v in vals.items():
        vec[layout.index(key)] = v
    return vec


def test_slo_trip_and_clear_events(event_log):
    """The staleness state machine: burn entry force-emits ingest_anomaly
    (with worst symbols + engine snapshot), every burning tick counts,
    recovery emits ingest_recovered, healthy digests sample at the
    cadence."""
    mon, _ = _mk_monitor(budget=1)
    mon.event_every = 4
    snap = {"marker": True}
    for _ in range(2):  # healthy: under budget
        d = mon.observe_digest(_digest_vec(stale5=1), tick_ms=1,
                               snapshot_fn=lambda: snap)
        assert d["stale_total"] == 1
    assert mon.anomaly_ticks == 0 and not mon.burning
    for i in range(5):  # burning: 2 + 1 > budget
        mon.observe_digest(_digest_vec(stale5=2, stale15=1), tick_ms=2 + i,
                           snapshot_fn=lambda: snap)
    assert mon.burning and mon.anomaly_ticks == 5
    mon.observe_digest(_digest_vec(), tick_ms=10)  # recovered
    assert not mon.burning and mon.recoveries == 1

    events = _read_events(event_log)
    kinds = [e["event"] for e in events]
    anomalies = [e for e in events if e["event"] == "ingest_anomaly"]
    # entry + one cadence re-emit (tick 4 of the burn), not one per tick
    assert len(anomalies) == 2
    assert anomalies[0]["stale_rows"] == 3
    assert anomalies[0]["budget"] == 1
    assert anomalies[0]["engine"] == {"marker": True}
    assert "worst_symbols" in anomalies[0]
    assert kinds[-1] == "ingest_recovered"
    assert events[-1]["burn_ticks"] == 5


def test_debug_symbols_route(event_log):
    from binquant_tpu.obs.exposition import MetricsServer

    mon, _ = _mk_monitor()
    mon.note_applied_batch(
        "15m", np.arange(4, dtype=np.int64),
        np.full(4, 900_000, np.int64), np.full(4, -1, np.int64),
    )
    server = MetricsServer(health_fn=lambda: {"status": "ok"}, ingest=mon)

    def get(target):
        raw = server._route(target)
        head, body = raw.split(b"\r\n\r\n", 1)
        return head.decode().split()[1], json.loads(body)

    status, payload = get("/debug/symbols?limit=2")
    assert status == "200"
    assert payload["enabled"] is True
    assert payload["total"] == 4
    assert len(payload["symbols"]) == 2
    status, payload = get("/debug/symbols?offset=3&limit=10")
    assert len(payload["symbols"]) == 1
    status, payload = get("/debug/symbols?limit=junk")
    assert status == "400"
    # unconfigured/disabled: a JSON no-op, never a 500
    bare = MetricsServer(health_fn=lambda: {"status": "ok"})
    raw = bare._route("/debug/symbols")
    body = json.loads(raw.split(b"\r\n\r\n", 1)[1])
    assert body == {"enabled": False, "symbols": []}
    # a crashing scoreboard must not read as success to probes
    mon.symbols_report = lambda **kw: (_ for _ in ()).throw(RuntimeError())
    status, payload = get("/debug/symbols")
    assert status == "500"
    assert payload == {"error": "symbols_report_failed"}


GOLDEN_EVENTS = [
    {
        "event": "ingest_digest",
        "digest": {
            "tracked": 8,
            "5m": {
                "stale_1x": 0, "stale_3x": 0, "stale_10x": 0,
                "max_age_s": 0.0, "covered": 8, "min_bars": 8, "fresh": 8,
                "appends": 24, "rewrites": 0, "gap_appends": 0, "dropped": 0,
            },
            "15m": {
                "stale_1x": 0, "stale_3x": 0, "stale_10x": 0,
                "max_age_s": 0.0, "covered": 8, "min_bars": 8, "fresh": 8,
                "appends": 8, "rewrites": 0, "gap_appends": 0, "dropped": 0,
            },
            "stale_total": 0,
        },
    },
    {
        "event": "ingest_anomaly",
        "tick_ms": 1780372800000,
        "stale_rows": 4,
        "budget": 0,
        "digest": {
            "tracked": 8,
            "5m": {
                "stale_1x": 2, "stale_3x": 1, "stale_10x": 0,
                "max_age_s": 3600.0, "covered": 8, "min_bars": 8, "fresh": 6,
                "appends": 18, "rewrites": 0, "gap_appends": 0, "dropped": 0,
            },
            "15m": {
                "stale_1x": 2, "stale_3x": 0, "stale_10x": 0,
                "max_age_s": 1800.0, "covered": 8, "min_bars": 8, "fresh": 6,
                "appends": 6, "rewrites": 0, "gap_appends": 0, "dropped": 0,
            },
            "stale_total": 4,
        },
        "worst_symbols": [
            {
                "symbol": "S003USDT", "row": 3, "score": 0.3333,
                "age_s": {"5m": 3600, "15m": 1800},
                "gaps": 0, "out_of_order": 0, "churn": 0,
            },
        ],
    },
    {
        "event": "ingest_recovered",
        "tick_ms": 1780374600000,
        "burn_ticks": 2,
        "digest": {
            "tracked": 8,
            "5m": {
                "stale_1x": 0, "stale_3x": 0, "stale_10x": 0,
                "max_age_s": 0.0, "covered": 8, "min_bars": 8, "fresh": 8,
                "appends": 36, "rewrites": 0, "gap_appends": 2, "dropped": 0,
            },
            "15m": {
                "stale_1x": 0, "stale_3x": 0, "stale_10x": 0,
                "max_age_s": 0.0, "covered": 8, "min_bars": 8, "fresh": 8,
                "appends": 12, "rewrites": 0, "gap_appends": 2, "dropped": 0,
            },
            "stale_total": 0,
        },
    },
]

GOLDEN_REPORT = """\
== ingest digest (latest) ==
  source ingest_recovered  tracked 8  stale_total 0
  5m   stale 1x/3x/10x 0/0/0  max_age      0s  covered    8  min_bars    8  fresh    8
       appends    36  rewrites    0  gap_appends    2  dropped    0
  15m  stale 1x/3x/10x 0/0/0  max_age      0s  covered    8  min_bars    8  fresh    8
       appends    12  rewrites    0  gap_appends    2  dropped    0

== staleness SLO timeline ==
  BURN  tick_ms   1780372800000  stale_rows    4  budget 0
  CLEAR tick_ms   1780374600000  after 2 burning tick(s)

== worst symbols (latest anomaly) ==
  S003USDT     score  0.3333  age5   3600s  age15   1800s  gaps   0  ooo   0  churn  0"""


def test_ingest_report_golden(tmp_path, capsys):
    """tools/ingest_report.py renders a deterministic report (format
    pinned like health_report's golden)."""
    import sys

    sys.path.insert(0, "tools")
    try:
        import ingest_report
    finally:
        sys.path.pop(0)

    log = tmp_path / "events.jsonl"
    log.write_text(
        "\n".join(json.dumps(e) for e in GOLDEN_EVENTS) + "\n"
        + "not json\n"
    )
    assert ingest_report.main([str(log)]) == 0
    out = capsys.readouterr().out.rstrip("\n")
    assert out == GOLDEN_REPORT

    assert ingest_report.main([str(log), "--json"]) == 0
    model = json.loads(capsys.readouterr().out)
    assert model["digest"]["stale_total"] == 0
    assert model["anomalies"][0]["stale_rows"] == 4
    assert model["worst_symbols"][0]["symbol"] == "S003USDT"


def test_health_report_ingest_section(tmp_path, capsys):
    """tools/health_report.py gains an ingest section — rendered only when
    ingest events exist, so pre-observatory logs render byte-identically."""
    import sys

    sys.path.insert(0, "tools")
    try:
        import health_report
    finally:
        sys.path.pop(0)

    log = tmp_path / "events.jsonl"
    log.write_text("\n".join(json.dumps(e) for e in GOLDEN_EVENTS) + "\n")
    assert health_report.main([str(log)]) == 0
    out = capsys.readouterr().out
    assert "== ingest health (latest digest) ==" in out
    assert "anomaly_events 1  recoveries 1" in out

    # a log with no ingest events renders no ingest section
    log2 = tmp_path / "plain.jsonl"
    log2.write_text(json.dumps({"event": "compile", "executable": "x",
                                "seconds": 1.0, "cache": "cold"}) + "\n")
    assert health_report.main([str(log2)]) == 0
    assert "ingest health" not in capsys.readouterr().out


def test_healthz_ingest_section_and_degraded_status(tmp_path):
    """/healthz grows an ingest section; a burning staleness SLO degrades
    the status (alive-but-impaired — stays probe-passing per the PR-1
    contract, which only 503s on stale heartbeats)."""
    from binquant_tpu.io.replay import make_stub_engine

    eng = make_stub_engine(capacity=8, window=112, ingest_digest=True)
    eng.touch_heartbeat()
    snap = eng.health_snapshot()
    assert snap["ingest"]["enabled"] is True
    assert snap["ingest"]["status"] == "ok"
    assert snap["status"] == "ok"
    eng.ingest_monitor.burning = True
    snap = eng.health_snapshot()
    assert snap["ingest"]["status"] == "degraded"
    assert snap["status"] == "degraded"
    # observatory off: section reports off, wires nothing
    eng2 = make_stub_engine(capacity=8, window=112, ingest_digest=False)
    eng2.touch_heartbeat()
    snap2 = eng2.health_snapshot()
    assert snap2["ingest"]["enabled"] is False
    assert snap2["ingest"]["status"] == "off"
    assert snap2["status"] == "ok"


@pytest.mark.slow
def test_churn_rewrite_stream_drill(tmp_path):
    """Slow lane (make ingest-smoke): a stream carrying a rewrite storm
    AND a listing wave, driven serial + scanned with the digest on —
    per-tick digests stay bit-identical (the storm ticks re-enter the
    serial path in both drives), the digest counts the rewrites, and the
    monitor sees the churn + out-of-order deliveries."""
    from binquant_tpu.io.replay import signal_tuples
    from binquant_tpu.sim.scenarios import (
        SCENARIOS,
        ScenarioSpec,
        base_market,
        emit_stream,
        listing_churn,
        rewrite_storm,
    )

    spec = ScenarioSpec(name="_drill", description="", n_symbols=10,
                        n_ticks=40, capacity=16, window=112, scan_chunk=8)
    closes, vols, _ = base_market(spec)
    klines = emit_stream(spec, closes, vols)
    rewrite_storm(klines, [spec.n_ticks - 6, spec.n_ticks - 4], per_tick=2)
    # the listing lands MID-chunk on purpose: the churn break strands a
    # too-short plan (3 buffered ticks < _SCAN_MIN_TICKS) that re-drives
    # serially AFTER the churn drain already claimed the newcomer's row.
    # Each re-driven tick now dispatches with its plan-time `tracked`
    # snapshot (_redrive_serial), so the digest's tracked count stays
    # bit-identical to the serial drive — this drill used to pin the
    # listing onto the chunk boundary (empty stranded plan) to dodge
    # exactly that diff
    listing_churn(
        klines, listings={8: 28}, delistings={}, n_symbols=spec.n_symbols
    )
    path = tmp_path / "churny.jsonl"
    with open(path, "w") as f:
        for k in klines:
            f.write(json.dumps(k) + "\n")

    eng_s, sig_s = _drive("serial", path, incremental=True)
    eng_c, sig_c = _drive("scanned", path, incremental=True)
    ds = np.stack(eng_s.ingest_monitor.digests)
    dc = np.stack(eng_c.ingest_monitor.digests)
    assert ds.shape == dc.shape
    assert np.array_equal(ds.view(np.int32), dc.view(np.int32))
    assert set(signal_tuples(sig_s)) == set(signal_tuples(sig_c))
    # the storm's corrected re-sends decode as rewrites in the digest
    decoded = [decode_ingest_digest(v) for v in ds]
    assert sum(d["15m"]["rewrites"] for d in decoded) >= 4
    # and as out-of-order deliveries + churn on the host monitor
    assert eng_s.ingest_monitor.out_of_order.sum() >= 4
    assert eng_s.ingest_monitor.churn_total >= 1
    assert eng_c.ingest_monitor.churn_total == eng_s.ingest_monitor.churn_total
    assert "feed_outage" in SCENARIOS and "breadth_stall" in SCENARIOS
