"""Telegram alert sink.

Covers the capability surface of the reference Telegram consumer
(``/root/reference/consumers/telegram_consumer.py``): HTML-safe message
rendering limited to Telegram's supported tags, content-derived duplicate
suppression with a 900 s cooldown, a paced single-flight send channel with
flood-control backoff, and fire-and-forget dispatch. The implementation is
original: sanitization is a single-pass tokenizer over the *raw* message
(the reference escapes everything and then un-escapes a whitelist), dedupe
is a parsed ``SignalFingerprint`` admitted through a ``CooldownLedger``,
and transport is an injected async callable (httpx by default) so tests
never touch the network.

Behavior contract pinned by tests/test_telegram_deep.py and
tests/test_io.py:
- whitelisted tags (b/strong/i/em/u/s/code/pre/a) survive verbatim;
  ``<a href='u'>`` is normalized to double quotes; ``<pre lang=x>`` keeps
  attribute text only when it carries no quoting/entity characters;
  pre-escaped entities (&lt; &gt; &amp; &quot; &#x27;) pass through;
  everything else is entity-escaped.
- two messages collide iff their (algo, symbol, Action, Strategy,
  Autotrade-route, autotrade-enabled-flag) extraction collides; a message
  with none of those fields dedupes on its full content hash.
- at most one send per second, serialized, retrying on flood control with
  a 2 s pad.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import re
import time
from collections.abc import Awaitable, Callable
from dataclasses import dataclass

from binquant_tpu.obs.instruments import SINK_EMISSIONS

log = logging.getLogger(__name__)

TransportFn = Callable[[str, str], Awaitable[None]]


class RetryAfterError(Exception):
    """Raised by a transport when Telegram flood control asks us to wait."""

    def __init__(self, retry_after: float) -> None:
        super().__init__(f"retry after {retry_after}s")
        self.retry_after = retry_after


# ---------------------------------------------------------------------------
# Sanitizer: one tokenizing scan over the raw message.
#
# Rather than escaping the whole string and then carving a whitelist back
# out of entity-space, classify each region of the raw text directly:
# known-safe markup and already-encoded entities are emitted as-is, every
# other character is escaped. One regex pass, no re-entrant substitutions.
# ---------------------------------------------------------------------------

_TELEGRAM_TAGS = ("b", "strong", "i", "em", "u", "s", "code", "pre", "a")

_CHAR_ENTITIES = {
    "&": "&amp;",
    "<": "&lt;",
    ">": "&gt;",
    '"': "&quot;",
    "'": "&#x27;",
}

_KNOWN_ENTITY = r"&(?:lt|gt|amp|quot|#x27);"

_TOKEN_SCANNER = re.compile(
    # plain open/close form of any supported tag, e.g. <strong> </a>
    rf"(?P<tag></?(?:{'|'.join(_TELEGRAM_TAGS)})>)"
    # anchor with a quoted href (either quote style; emitted double-quoted)
    r"|(?P<anchor><a\s+href=['\"](?P<href>.+?)['\"]>)"
    # pre/code carrying attribute text free of quoting/entity characters
    r"|(?P<fenced><(?P<fence>pre|code)\s+(?P<fattrs>[^&<>'\"]*)>)"
    # an entity the author already encoded; passes through untouched
    rf"|(?P<entity>{_KNOWN_ENTITY})"
)

_ENTITY_OR_CHAR = re.compile(rf"({_KNOWN_ENTITY})|(.)", re.S)


def _escape_segment(text: str) -> str:
    """Entity-escape plain text, letting already-encoded entities stand."""
    return _ENTITY_OR_CHAR.sub(
        lambda m: m.group(1) or _CHAR_ENTITIES.get(m.group(2), m.group(2)),
        text,
    )


def sanitize_telegram_html(message: str) -> str:
    out: list[str] = []
    cursor = 0
    for token in _TOKEN_SCANNER.finditer(message):
        out.append(_escape_segment(message[cursor : token.start()]))
        if token.group("tag") or token.group("entity"):
            out.append(token.group(0))
        elif token.group("anchor"):
            out.append(f'<a href="{_escape_segment(token.group("href"))}">')
        else:  # fenced: attribute text is verified entity-free by the regex
            out.append(f"<{token.group('fence')} {token.group('fattrs')}>")
        cursor = token.end()
    out.append(_escape_segment(message[cursor:]))
    return "".join(out)


# ---------------------------------------------------------------------------
# Duplicate suppression: parse once into a fingerprint, admit via a ledger.
# ---------------------------------------------------------------------------

_HASHTAG = re.compile(r"#([A-Za-z0-9_]+)")
_ALGO_HEADER = re.compile(r"<strong>#([^<\s]+)\s+algorithm</strong>")
_KEYED_FIELDS = ("Action", "Strategy", "Autotrade route")


@dataclass(frozen=True)
class SignalFingerprint:
    """The identity of an alert for dedupe purposes.

    Extraction targets the structured message layout every emission uses
    (``- Label: value`` bullet lines, a ``#algo algorithm`` header, a
    trailing ``#SYMBOL`` hashtag, and the autotrade enabled/disabled
    sentence). Messages that expose none of those collapse to a content
    digest, so free-form digests still dedupe on exact repetition.
    """

    algo: str = ""
    symbol: str = ""
    action: str = ""
    strategy: str = ""
    route: str = ""
    autotrade: str = ""
    digest: str = ""

    def key(self) -> tuple[str, ...]:
        structured = (
            self.algo,
            self.symbol,
            self.action,
            self.strategy,
            self.route,
            self.autotrade,
        )
        if any(structured):
            return structured
        return ("digest", self.digest)


def parse_fingerprint(condensed: str) -> SignalFingerprint:
    bullets: dict[str, str] = {}
    for line in condensed.splitlines():
        if not line.startswith("- "):
            continue
        label, sep, value = line[2:].partition(":")
        if sep and label in _KEYED_FIELDS:
            bullets.setdefault(label, value.strip())

    tags = _HASHTAG.findall(condensed)
    header = _ALGO_HEADER.search(condensed)

    if "Autotrade is enabled" in condensed:
        autotrade = "enabled"
    elif "Autotrade is disabled" in condensed:
        autotrade = "disabled"
    else:
        autotrade = ""

    return SignalFingerprint(
        algo=header.group(1) if header else "",
        symbol=tags[-1] if tags else "",
        action=bullets.get("Action", ""),
        strategy=bullets.get("Strategy", ""),
        route=bullets.get("Autotrade route", ""),
        autotrade=autotrade,
        digest=hashlib.sha1(condensed.encode("utf-8")).hexdigest(),
    )


class CooldownLedger:
    """Admission control over fingerprint keys.

    Two layers: an *in-flight* set (a key currently being sent is never
    re-admitted, regardless of TTL) and a *sent-at* map enforcing a
    cooldown window. A non-positive TTL disables the window, leaving
    in-flight suppression only.
    """

    def __init__(self) -> None:
        self._sent_at: dict[tuple[str, ...], float] = {}
        self._inflight: set[tuple[str, ...]] = set()

    def admit(self, key: tuple[str, ...], ttl: float) -> bool:
        if key in self._inflight:
            log.info("Telegram duplicate signal already pending; skipping")
            return False
        if ttl <= 0:
            self._inflight.add(key)
            return True

        now = time.monotonic()
        for stale in [k for k, at in self._sent_at.items() if now - at >= ttl]:
            del self._sent_at[stale]

        if key in self._sent_at:
            log.info("Telegram duplicate signal inside cooldown; skipping")
            return False
        self._sent_at[key] = now
        self._inflight.add(key)
        return True

    def release(self, key: tuple[str, ...]) -> None:
        self._inflight.discard(key)

    def forget(self, key: tuple[str, ...]) -> None:
        """Drop a key's cooldown stamp — a FAILED send must not suppress
        the delivery plane's retry of the same message as a duplicate
        (admit records the stamp at admission, not at send success)."""
        self._sent_at.pop(key, None)


# ---------------------------------------------------------------------------
# Transport + consumer
# ---------------------------------------------------------------------------


def httpx_bot_transport(token: str) -> TransportFn:
    """Production transport: Bot API sendMessage over httpx."""
    import httpx

    endpoint = f"https://api.telegram.org/bot{token}/sendMessage"

    async def post(chat_id: str, text: str) -> None:
        async with httpx.AsyncClient(timeout=10) as client:
            reply = await client.post(
                endpoint,
                json={"chat_id": chat_id, "text": text, "parse_mode": "HTML"},
            )
            if reply.status_code == 429:
                pause = reply.json().get("parameters", {}).get("retry_after", 5)
                raise RetryAfterError(float(pause))
            reply.raise_for_status()

    return post


def _condense(message: str) -> str:
    """Strip indentation and blank lines (messages are triple-quoted)."""
    return "\n".join(ln.strip() for ln in message.splitlines() if ln.strip())


class TelegramConsumer:
    _MIN_SEND_INTERVAL_SECONDS = 1.0
    _RETRY_AFTER_PAD_SECONDS = 2.0
    _SIGNAL_DEDUPE_SECONDS = 900.0

    def __init__(
        self,
        token: str,
        chat_id: str,
        is_enabled: bool = True,
        transport: TransportFn | None = None,
    ) -> None:
        self.chat_id = chat_id
        self.is_enabled = is_enabled
        if transport is None and token:
            transport = httpx_bot_transport(token)
        self._transport = transport
        self._ledger = CooldownLedger()
        self._send_lock = asyncio.Lock()
        self._min_send_interval_seconds = self._MIN_SEND_INTERVAL_SECONDS
        self._retry_after_pad_seconds = self._RETRY_AFTER_PAD_SECONDS
        self._signal_dedupe_seconds = self._SIGNAL_DEDUPE_SECONDS
        self._sent_monotonic: float | None = None
        # Hold strong refs so fire-and-forget tasks survive GC mid-send.
        self._background_tasks: set[asyncio.Task] = set()

    # The method name is part of the tested surface; logic lives above.
    def _sanitize_html(self, message: str) -> str:
        return sanitize_telegram_html(message)

    async def _pace(self) -> None:
        if self._sent_monotonic is None or self._min_send_interval_seconds <= 0:
            return
        due = self._sent_monotonic + self._min_send_interval_seconds
        wait = due - time.monotonic()
        if wait > 0:
            await asyncio.sleep(wait)

    async def send_msg(self, message: str) -> None:
        """Deliver one message, serialized, paced, flood-control aware."""
        if self._transport is None:
            return
        text = sanitize_telegram_html(message)
        async with self._send_lock:
            while True:
                await self._pace()
                try:
                    await self._transport(self.chat_id, text)
                except RetryAfterError as flood:
                    SINK_EMISSIONS.labels(sink="telegram", outcome="retry").inc()
                    pause = flood.retry_after + self._retry_after_pad_seconds
                    log.warning(
                        "Telegram flood control active; retrying in %.1fs", pause
                    )
                    await asyncio.sleep(pause)
                    continue
                self._sent_monotonic = time.monotonic()
                SINK_EMISSIONS.labels(sink="telegram", outcome="ok").inc()
                return

    async def send_signal(self, message: str) -> None:
        """send_msg that swallows every error (alerting must never crash)."""
        try:
            condensed = _condense(message)
            if condensed:
                await self.send_msg(condensed)
        except Exception as exc:
            SINK_EMISSIONS.labels(sink="telegram", outcome="error").inc()
            log.error("Error sending telegram signal: %s", exc)
            log.error("Original message: %s", message)

    async def deliver_signal(self, message: str) -> bool:
        """Delivery-plane entry point (io/delivery.py TelegramSink): the
        same admission control as ``dispatch_signal``, but awaited and
        RAISING on transport failure so the plane's retry/backoff and
        circuit breaker own the error instead of a swallowed log line.
        Returns False when disabled, empty, or suppressed as a duplicate
        (all successful no-op deliveries)."""
        if not self.is_enabled or self._transport is None:
            return False
        condensed = _condense(message)
        if not condensed:
            return False
        key = parse_fingerprint(condensed).key()
        if not self._ledger.admit(key, self._signal_dedupe_seconds):
            SINK_EMISSIONS.labels(sink="telegram", outcome="suppressed").inc()
            return False
        try:
            await self.send_msg(condensed)
            return True
        except BaseException as exc:
            if isinstance(exc, Exception):
                SINK_EMISSIONS.labels(sink="telegram", outcome="error").inc()
            # a failed send — or one cancelled by the plane's per-attempt
            # deadline (CancelledError is a BaseException) — must not hold
            # the cooldown window against the retry of the very same
            # message, else the retry is suppressed as a duplicate and
            # acked without ever sending
            self._ledger.forget(key)
            raise
        finally:
            self._ledger.release(key)

    def dispatch_signal(self, message: str) -> asyncio.Task | None:
        """Fire-and-forget entry point used by the emission path.

        Returns the created task (kept alive in ``_background_tasks``), or
        None when disabled, empty, or suppressed as a duplicate.
        """
        if not self.is_enabled:
            return None
        condensed = _condense(message)
        if not condensed:
            return None
        key = parse_fingerprint(condensed).key()
        if not self._ledger.admit(key, self._signal_dedupe_seconds):
            SINK_EMISSIONS.labels(sink="telegram", outcome="suppressed").inc()
            return None

        task = asyncio.create_task(self.send_signal(condensed))
        self._background_tasks.add(task)

        def _done(t: asyncio.Task, key: tuple[str, ...] = key) -> None:
            self._background_tasks.discard(t)
            self._ledger.release(key)

        task.add_done_callback(_done)
        return task
