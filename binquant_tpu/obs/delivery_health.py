"""Delivery-plane health collector: close→sink-ack lag + SLO feed (ISSUE 16).

The last observatory gap: since the delivery plane (PR 13) moved sink
round trips off the tick thread, the freshness SLO measured
candle-close→**enqueue**, not close→**delivered**. This collector is the
ack-side consumer — :meth:`DeliveryHealth.on_ack` is called by
``DeliveryPlane._ack`` with the end-to-end lag of every confirmed
delivery (measured to the FINAL successful ack, retries and queue dwell
included; replayed entries carry their original candle-close anchor
through the WAL record, so a kill-and-restore redelivery reports the
true cross-process lag):

* ``bqt_delivery_lag_ms{sink}`` — the per-sink close→ack histogram;
* a rolling per-sink sample window feeding the p99 the delivery SLO is
  judged against (``BQT_DELIVERY_SLO_MS`` budget, one ``delivery.<sink>``
  SLO minted lazily per sink in the unified registry — obs/slo.py owns
  the burn/recover event model).

The collector is ack-driven only — it adds nothing to the tick thread
(the anchors ride the existing WAL put records and enqueue arguments).
Disabled instances are allocation-free no-ops, the BQT_TRACE_SAMPLE
pattern.
"""

from __future__ import annotations

from collections import deque

from binquant_tpu.obs.instruments import DELIVERY_LAG


def _p99(samples) -> float:
    """Nearest-rank p99 of a small sample window (no numpy on the ack
    path — workers are plain asyncio coroutines)."""
    ordered = sorted(samples)
    idx = max(int(len(ordered) * 0.99 + 0.5) - 1, 0)
    return ordered[min(idx, len(ordered) - 1)]


class DeliveryHealth:
    """Per-sink close→ack lag windows + the delivery-SLO feed."""

    def __init__(
        self,
        enabled: bool = True,
        window: int = 512,
        slo=None,
        slo_ms: float = 0.0,
    ) -> None:
        self.enabled = bool(enabled)
        self.window = max(int(window), 1)
        # the unified SloRegistry (obs/slo.py); None = lag histograms
        # only, no SLO judging
        self.slo = slo
        self.slo_ms = max(float(slo_ms), 0.0)
        self._lags: dict[str, deque] = {}
        self.acks: dict[str, int] = {}
        self.last_lag_ms: dict[str, float] = {}

    def on_ack(
        self,
        sink: str,
        lag_ms: float,
        attempts: int = 1,
        replayed: bool = False,
    ) -> None:
        """One confirmed delivery's end-to-end lag (close→final ack)."""
        if not self.enabled:
            return
        lag_ms = max(float(lag_ms), 0.0)
        DELIVERY_LAG.labels(sink=sink).observe(lag_ms)
        window = self._lags.get(sink)
        if window is None:
            window = self._lags[sink] = deque(maxlen=self.window)
        window.append(lag_ms)
        self.acks[sink] = self.acks.get(sink, 0) + 1
        self.last_lag_ms[sink] = lag_ms
        if self.slo is not None and self.slo_ms > 0:
            p99 = _p99(window)
            name = f"delivery.{sink}"
            self.slo.ensure(name, "delivery", self.slo_ms)
            self.slo.observe(
                name,
                ok=p99 <= self.slo_ms,
                sink=sink,
                p99_ms=round(p99, 3),
                lag_ms=round(lag_ms, 3),
                attempts=int(attempts),
                replayed=bool(replayed),
            )

    def p99(self, sink: str) -> float | None:
        window = self._lags.get(sink)
        return round(_p99(window), 3) if window else None

    def snapshot(self) -> dict:
        """The /healthz contribution: per-sink ack counts + lag summary
        (attribute reads + one small sort; safe inline on the event
        loop)."""
        return {
            "enabled": self.enabled,
            "slo_ms": self.slo_ms,
            "window": self.window,
            "sinks": {
                sink: {
                    "acks": self.acks.get(sink, 0),
                    "last_lag_ms": round(self.last_lag_ms.get(sink, 0.0), 3),
                    "p99_ms": self.p99(sink),
                }
                for sink in sorted(self._lags)
            },
        }
