"""The time-batched backtest kernel: T full-recompute ticks in one dispatch.

The scanned replay (engine/step.py ``tick_step_scan``) fuses T ticks into
one dispatch but still threads the carried per-tick recursion *serially*
through time — every tick's windowed math waits for the previous tick's
state. This backend exploits what a backtest knows up front (the whole
candle stream) to break that dependency:

* **Extended buffers**: the chunk's clean appends are laid out once as an
  ``(S, W+N)`` extension of the pre-chunk ring; the right-aligned window
  the serial drive would hold at tick t is exactly the column slice
  ``[c_t, c_t+W)`` where ``c_t`` counts that symbol's bars applied so far.
  Window views are gathers, bit-identical to the serial buffers.
* **Time-vectorized precompute**: everything context-free in the full
  tick — feature packs, symbol features, the LSP heavy core, the BTC
  beta/corr block — evaluates via ``vmap`` over the tick axis on those
  views, calling the SAME kernels the serial full path calls; the ABP
  heavy core (the dominant cost: full-tail rolling medians + quantile
  sorts) goes further and collapses the T heavily-overlapping per-tick
  tails into ONE extended-series pass (``abp_core_batch`` — bit-exact
  because every ABP rolling input is position-local and sort/shift based;
  LSP's cumsum-anchored means/extrema are NOT view-invariant in f32 and
  therefore stay vmapped). The windowed sorts/EWM matmuls for all T ticks
  run as one batched kernel each instead of T dependent dispatches.
* **Sequential residue**: only the genuinely cross-tick recursions remain
  in a ``lax.scan`` — the market-regime carry, PriceTracker/
  MeanReversionFade dedupe cooldowns, and the grid-only-policy feedback
  (the same device-side recursion the scanned drive carries) — each a few
  (S,)-sized ops per tick.

The chunk emits the SAME stacked ``(T, wire_length)`` wire format as
``tick_step_scan`` (one shared ``pack_wire``), so the standard host decode
(``unpack_wire`` → ``_finalize_tick`` → emission) consumes it unchanged,
and equality against the serial FULL-recompute drive is pinned end-to-end
on emitted signal sets (tests/test_backtest.py). NOTE the pin is against
the full path, NOT the carried fast path — the supertrend and ABP/beta-
corr carries have documented divergences from full recompute (CHANGES.md
PR 4/5 NOTEs) that a full-recompute backend must not inherit.

``vmap`` over a :class:`strategies.params.StrategyParams` float axis
(``backtest_chunk_sweep``) scores P parameter combos in the one dispatch;
everything params-independent (buffers, packs, features) has no batch dim
and is computed once.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from binquant_tpu.engine.buffer import Field, MarketBuffer, fresh_mask
from binquant_tpu.engine.step import (
    BC_WINDOW,
    LIVE_STRATEGIES,
    MIN_BARS,
    STRATEGY_ORDER,
    WIRE_FIRED_COUNT_OFF,
    WIRE_MAX_FIRED,
    HostInputs,
    _btc_change_96,
    _btc_momentum_pair,
    _btc_row_mask,
    _mask_outputs,
    _numeric_digest_block,
    build_summary,
    pack_wire,
    quiet_suppression,
    wire_length,
)
from binquant_tpu.ops.indicators import log_returns, rolling_beta_corr
from binquant_tpu.regime.context import (
    ContextConfig,
    RegimeCarry,
    compute_market_context,
    compute_symbol_features,
)
from binquant_tpu.strategies.activity_burst_pump import (
    _abp_outputs,
    abp_core_batch,
)
from binquant_tpu.strategies.base import no_signal
from binquant_tpu.strategies.dormant import inverse_price_tracker
from binquant_tpu.strategies.ladder_deployer import ladder_deployer
from binquant_tpu.strategies.liquidation_sweep_pump import (
    _lsp_outputs,
    _routing,
    lsp_core,
)
from binquant_tpu.strategies.mean_reversion_fade import mean_reversion_fade
from binquant_tpu.strategies.params import resolve_params
from binquant_tpu.strategies.price_tracker import price_tracker

# Strategies the backtest backend evaluates exactly. The live five plus the
# pack-only dormant InversePriceTracker; the remaining dormant kernels read
# raw buffer windows inside the *gated* half of the tick, which this
# backend's precompute/evaluate split does not thread through (enable them
# via the serial drives instead).
BACKTEST_STRATEGIES: frozenset[str] = frozenset(LIVE_STRATEGIES) | {
    "inverse_price_tracker"
}


class TickPre(NamedTuple):
    """One tick's context-free precompute — (S,)-scale leaves stacked to
    (T, ...) by the vmap, then consumed tick-by-tick by the scan."""

    fresh5: jnp.ndarray
    fresh15: jnp.ndarray
    filled5: jnp.ndarray
    filled15: jnp.ndarray
    pack5: object  # FeaturePack
    pack15: object
    feats15: object  # SymbolFeatureArrays (pre RS-vs-BTC rewrite)
    lsp_score_ok: jnp.ndarray
    lsp_trigger_score: jnp.ndarray
    lsp_threshold: jnp.ndarray
    lsp_volume_last: jnp.ndarray
    btc_beta: jnp.ndarray
    btc_corr: jnp.ndarray
    btc_mom: jnp.ndarray
    btc_change_96: jnp.ndarray


def _window_views(
    ext_times: jnp.ndarray,
    ext_vals: jnp.ndarray,
    counts: jnp.ndarray,  # (T, S)
    filled0: jnp.ndarray,
    window: int,
) -> MarketBuffer:
    """The right-aligned (S, W) rings the serial drive would hold at every
    tick, stacked to (T, S, W(, F)): tick t's window is columns
    ``[counts[t], counts[t]+window)`` of the extended arrays, gathered
    per-symbol (each row has its own offset — symbols miss bars
    independently).

    Built OUTSIDE the vmapped precompute and pinned behind an
    ``optimization_barrier``: XLA CPU otherwise fuses the gather into each
    of the pack/strategy kernels' ~30 window reads and re-executes it per
    consumer — the exact failure mode PR 5 measured at 7x on
    dynamic-slice views. The barrier materializes ONE (T, S, W, F) buffer
    that every consumer then reads. Returns a (T,)-leading MarketBuffer
    pytree (vmap consumes it with in_axes=0)."""
    T = counts.shape[0]
    cols = counts[:, :, None] + jnp.arange(window, dtype=jnp.int32)[None, None, :]
    times = jnp.take_along_axis(
        jnp.broadcast_to(ext_times[None], (T,) + ext_times.shape), cols, axis=2
    )
    vals = jnp.take_along_axis(
        jnp.broadcast_to(ext_vals[None], (T,) + ext_vals.shape),
        cols[:, :, :, None],
        axis=2,
    )
    times, vals = jax.lax.optimization_barrier((times, vals))
    filled = jnp.minimum(filled0[None, :] + counts, window).astype(jnp.int32)
    # gathered views are canonical right-aligned by construction
    return MarketBuffer(
        times=times,
        values=vals,
        filled=filled,
        cursor=jnp.zeros(filled.shape, jnp.int32),
    )


def _precompute_one(
    buf5: MarketBuffer,
    buf15: MarketBuffer,
    inp: HostInputs,
    sp,
) -> TickPre:
    """Everything the full tick computes that does NOT depend on the
    market context or any cross-tick carry — the same expressions as
    ``_tick_step_impl``'s full path, on one tick's gathered window views."""
    from binquant_tpu.strategies.features import compute_feature_pack

    fresh5 = fresh_mask(buf5, inp.timestamp5_s)
    fresh15 = fresh_mask(buf15, inp.timestamp_s)
    pack5 = compute_feature_pack(buf5)
    pack15 = compute_feature_pack(buf15)
    feats15 = compute_symbol_features(buf15, fresh15 & inp.tracked)

    # LSP's heavy core stays per-tick (vmapped): its rolling means/extrema
    # are cumsum/view-anchored, so an extended-series pass would differ by
    # f32 ulps from the serial kernel — and it is cheap (~6 ms/tick at
    # 256x120, vs ABP's ~140 ms, which IS shared — see abp_core_batch)
    lsp_score_ok, lsp_score, lsp_thr, lsp_vol = lsp_core(
        buf15, inp.oi_growth, sp.lsp
    )

    # --- BTC-relative block: expression-for-expression the full path's
    # else-branch in _tick_step_impl
    S = buf15.capacity
    W = buf15.times.shape[1]
    onehot_rows, btc_ok = _btc_row_mask(inp.btc_row, S)
    close15 = buf15.values[:, :, Field.CLOSE]
    rets = log_returns(close15)
    btc_onehot = onehot_rows[:, None]
    btc_rets_row = jnp.where(btc_onehot, rets, 0.0).sum(axis=0)
    btc_close_row = jnp.where(btc_onehot, close15, 0.0).sum(axis=0)
    btc_rets = jnp.where(btc_ok, btc_rets_row, jnp.nan)
    bc = rolling_beta_corr(rets, btc_rets[None, :], window=BC_WINDOW)
    btc_beta = jnp.where(jnp.isfinite(bc.beta[:, -1]), bc.beta[:, -1], 0.0)
    btc_corr = jnp.where(jnp.isfinite(bc.corr[:, -1]), bc.corr[:, -1], 0.0)
    btc_close = jnp.where(btc_ok, btc_close_row, jnp.nan)
    if W > 96:
        btc_change = _btc_change_96(btc_close[-1], btc_close[-97], btc_ok)
    else:
        btc_change = jnp.asarray(0.0, dtype=jnp.float32)
    btc_mom = _btc_momentum_pair(btc_close[-1], btc_close[-2])

    return TickPre(
        fresh5=fresh5,
        fresh15=fresh15,
        filled5=buf5.filled,
        filled15=buf15.filled,
        pack5=pack5,
        pack15=pack15,
        feats15=feats15,
        lsp_score_ok=lsp_score_ok,
        lsp_trigger_score=lsp_score,
        lsp_threshold=lsp_thr,
        lsp_volume_last=lsp_vol,
        btc_beta=btc_beta,
        btc_corr=btc_corr,
        btc_mom=btc_mom,
        btc_change_96=btc_change,
    )


def _precompute_ext(
    ext5: tuple[jnp.ndarray, jnp.ndarray],
    ext15: tuple[jnp.ndarray, jnp.ndarray],
    counts5: jnp.ndarray,
    counts15: jnp.ndarray,
    filled0: tuple[jnp.ndarray, jnp.ndarray],
    inputs_seq: HostInputs,  # (T, ...) leaves
    sp,
    window: int,
    wire_enabled: tuple[str, ...],
    times5_last: jnp.ndarray,  # (T, S) gathered last-bar open times
    times15_last: jnp.ndarray,
    filled5: jnp.ndarray,  # (T, S)
    filled15: jnp.ndarray,
) -> TickPre:
    """The extension-invariant TickPre: every position-local kernel runs
    ONCE over the (S, L = W + N) extended buffers instead of T times over
    gathered (T, S, W) window views (``BQT_EXT_INVARIANT=1`` — the
    governed twin of the vmapped ``_precompute_one``; see that docstring
    and README §Backtest for the gate-margin tolerance contract).

    Differences from the vmapped path, by design:

    * feature packs + symbol features come from the ``*_ext`` kernels
      (strategies/features.py, regime/context.py) — positional fields
      bit-identical, windowed/EWM fields ulp/margin-governed;
    * the (T, S, W, F) 5m view gather disappears entirely; the 15m views
      are materialized ONLY for LSP's cumsum-anchored heavy core (which
      stays vmapped — its means/extrema are not view-invariant in f32),
      and only when the strategy is enabled;
    * the BTC beta/corr block runs ONE ``rolling_beta_corr`` over the
      (S, L) extension against the single extended bench row — valid
      because the driver only routes chunks whose ``btc_row`` is constant
      across ticks here (non-constant chunks fall back to the vmapped
      precompute). The per-tick change_96/momentum closes are exact
      positional gathers at the BTC row's own extension counts.
    """
    from binquant_tpu.regime.context import compute_symbol_features_ext
    from binquant_tpu.strategies.features import (
        compute_feature_pack_ext,
        ext_gather,
    )

    fresh5 = (filled5 > 0) & (times5_last == inputs_seq.timestamp5_s[:, None])
    fresh15 = (filled15 > 0) & (times15_last == inputs_seq.timestamp_s[:, None])

    pack5 = compute_feature_pack_ext(
        ext5[0], ext5[1], counts5, filled0[0], window
    )
    pack15 = compute_feature_pack_ext(
        ext15[0], ext15[1], counts15, filled0[1], window
    )
    feats15 = compute_symbol_features_ext(
        ext15[0], ext15[1], counts15, filled0[1], window,
        fresh15 & inputs_seq.tracked,
    )

    T, S = counts15.shape
    if "liquidation_sweep_pump" in wire_enabled:
        # LSP's heavy core is the one per-tick residue: cumsum/view-anchored
        # means/extrema (see _precompute_one). Gather the 15m views for it
        # alone — the packs/feats above no longer need them.
        views15 = _window_views(*ext15, counts15, filled0[1], window)
        lsp_score_ok, lsp_score, lsp_thr, lsp_vol = jax.vmap(
            lambda b15, oi: lsp_core(b15, oi, sp.lsp)
        )(views15, inputs_seq.oi_growth)
    else:
        zeros = jnp.zeros((T, S), jnp.float32)
        lsp_score_ok, lsp_score, lsp_thr, lsp_vol = (
            jnp.zeros((T, S), bool), zeros, zeros, zeros,
        )

    # --- BTC-relative block over the extension (btc_row constant across
    # the chunk — the driver's routing invariant for this path)
    last15 = (counts15 + (window - 1)).astype(jnp.int32)
    onehot_rows, btc_ok = _btc_row_mask(inputs_seq.btc_row[0], S)
    close15 = ext15[1][:, :, Field.CLOSE]
    rets = log_returns(close15)  # position-local → elementwise exact
    btc_onehot = onehot_rows[:, None]
    btc_rets_row = jnp.where(btc_onehot, rets, 0.0).sum(axis=0)  # (L,)
    btc_close_row = jnp.where(btc_onehot, close15, 0.0).sum(axis=0)
    btc_rets = jnp.where(btc_ok, btc_rets_row, jnp.nan)
    bc = rolling_beta_corr(rets, btc_rets[None, :], window=BC_WINDOW)
    beta_g = ext_gather(bc.beta, last15)
    corr_g = ext_gather(bc.corr, last15)
    btc_beta = jnp.where(jnp.isfinite(beta_g), beta_g, 0.0)
    btc_corr = jnp.where(jnp.isfinite(corr_g), corr_g, 0.0)
    btc_close = jnp.where(btc_ok, btc_close_row, jnp.nan)  # (L,)
    btc_counts = (counts15 * onehot_rows[None, :]).sum(axis=1)  # (T,)
    p = (btc_counts + (window - 1)).astype(jnp.int32)
    if window > 96:
        btc_change = _btc_change_96(btc_close[p], btc_close[p - 96], btc_ok)
    else:
        btc_change = jnp.zeros((T,), jnp.float32)
    btc_mom = _btc_momentum_pair(btc_close[p], btc_close[p - 1])

    return TickPre(
        fresh5=fresh5,
        fresh15=fresh15,
        filled5=filled5,
        filled15=filled15,
        pack5=pack5,
        pack15=pack15,
        feats15=feats15,
        lsp_score_ok=lsp_score_ok,
        lsp_trigger_score=lsp_score,
        lsp_threshold=lsp_thr,
        lsp_volume_last=lsp_vol,
        btc_beta=btc_beta,
        btc_corr=btc_corr,
        btc_mom=btc_mom,
        btc_change_96=btc_change,
    )


def _evaluate_tick(
    pre: TickPre,
    abp_pre: tuple,
    inp: HostInputs,
    regime_carry: RegimeCarry,
    mrf_carry: jnp.ndarray,
    pt_carry: jnp.ndarray,
    cfg: ContextConfig,
    wire_enabled: tuple[str, ...],
    sp,
    numeric_digest: bool = False,
    ingest_block=None,
):
    """The gated half of the full tick from precomputed features: market
    context (same ``compute_market_context``, symbol features injected),
    the strategy gates, and the shared wire packing. Mirrors
    ``_tick_step_impl``'s post-precompute structure line for line
    (including the trailing numeric-health digest when that static flag
    is on — the backtest wires decode through the same finalize path)."""
    S = pre.filled15.shape[0]
    from binquant_tpu.engine.buffer import NUM_FIELDS

    # compute_market_context with injected feats reads only capacity +
    # filled from the buffer — a thin (S, 1) shell carries both
    thin15 = MarketBuffer(
        times=jnp.zeros((S, 1), jnp.int32),
        values=jnp.zeros((S, 1, NUM_FIELDS), jnp.float32),
        filled=pre.filled15,
        cursor=jnp.zeros((S,), jnp.int32),
    )
    context, regime_carry2 = compute_market_context(
        thin15,
        pre.fresh15,
        inp.tracked,
        inp.btc_row,
        inp.timestamp_s,
        regime_carry,
        cfg,
        feats=pre.feats15,
    )

    ok5 = pre.pack5.filled >= MIN_BARS
    ok15 = pre.pack15.filled >= MIN_BARS
    quiet_suppressed = quiet_suppression(context, inp.quiet_hours)
    skipped = no_signal(S)

    def want(name: str) -> bool:
        return name in wire_enabled

    abp_qualified, abp_score, abp_diag = abp_pre
    abp = (
        _mask_outputs(
            _abp_outputs(
                pre.filled5, context, abp_qualified, abp_score, abp_diag,
                sp.abp,
            ),
            ok5 & pre.fresh5,
        )
        if want("activity_burst_pump")
        else skipped
    )
    pt, pt_carry2 = price_tracker(
        pre.pack5, context, quiet_suppressed, pt_carry, params=sp.pt
    )
    pt = _mask_outputs(pt, ok5 & pre.fresh5)
    pt_carry2 = jnp.where(ok5 & pre.fresh5, pt_carry2, pt_carry)

    if want("liquidation_sweep_pump"):
        routed, short_ok, route, _ = _routing(
            context, inp.adp_latest, inp.adp_prev, pre.btc_mom, sp.lsp
        )
        lsp = _mask_outputs(
            _lsp_outputs(
                pre.filled15, pre.lsp_score_ok, pre.lsp_trigger_score,
                pre.lsp_threshold, routed, short_ok, route, inp.oi_growth,
                inp.adp_latest, pre.btc_mom, pre.lsp_volume_last, sp.lsp,
            ),
            ok15 & pre.fresh15,
        )
    else:
        lsp = skipped
    mrf, mrf_carry2 = mean_reversion_fade(
        pre.pack15, inp.is_futures, mrf_carry, sp.mrf
    )
    mrf = _mask_outputs(mrf, ok15 & pre.fresh15)
    mrf_carry2 = jnp.where(ok15 & pre.fresh15, mrf_carry2, mrf_carry)
    ladder = (
        _mask_outputs(
            ladder_deployer(
                pre.pack15, context, inp.grid_policy_allows, inp.is_futures,
                sp.ladder,
            ),
            ok15 & pre.fresh15,
        )
        if want("grid_ladder")
        else skipped
    )
    ipt = (
        _mask_outputs(inverse_price_tracker(pre.pack5, context), ok5 & pre.fresh5)
        if want("inverse_price_tracker")
        else skipped
    )

    strategies = {
        "activity_burst_pump": abp,
        "coinrule_price_tracker": pt,
        "liquidation_sweep_pump": lsp,
        "mean_reversion_fade": mrf,
        "grid_ladder": ladder,
        "coinrule_supertrend_swing_reversal": skipped,
        "coinrule_twap_momentum_sniper": skipped,
        "coinrule_buy_low_sell_high": skipped,
        "coinrule_buy_the_dip": skipped,
        "bb_extreme_reversion": skipped,
        "inverse_price_tracker": ipt,
        "range_bb_rsi_mean_reversion": skipped,
        "range_failed_breakout_fade": skipped,
        "relative_strength_reversal_range": skipped,
    }
    summary = build_summary(strategies)
    if numeric_digest:
        digest = _numeric_digest_block(
            pre.pack5, pre.pack15, summary, pre.btc_beta, pre.btc_corr,
            inp.tracked, ok5, ok15, pre.fresh5, pre.fresh15,
            jnp.zeros((S,), bool),  # full path: no expected-NaN beta rows
            # classic/full-recompute semantics: the same wire-materialized
            # field subset the serial classic step counts (engine/step.py)
            wire_fields_only=True,
            # margin-proximity fields (ISSUE 17): same sp/context the
            # serial call site passes, so blocks stay backend-identical
            sp=sp,
            context=context,
        )
    else:
        digest = None
    wire = pack_wire(
        context, strategies, summary, pre.pack5, pre.pack15,
        pre.btc_beta, pre.btc_corr, pre.btc_change_96,
        jnp.asarray(0.0, dtype=jnp.float32),  # full path: no dirty bc rows
        wire_enabled,
        digest=digest,
        # ingest-health block (ISSUE 15): assembled OUTSIDE the scan from
        # the per-tick window views + cumulative extension counts
        # (_chunk_ingest_stats/_chunk_ingest_counts) and threaded in as a
        # scan input — packed last, exactly like the serial step
        ingest=ingest_block,
    )
    enabled_mask = jnp.asarray(
        [s in wire_enabled for s in STRATEGY_ORDER], dtype=bool
    )
    trig_counts = jnp.sum(
        summary.trigger & enabled_mask[:, None], axis=1
    ).astype(jnp.int32)
    at_counts = jnp.sum(
        summary.autotrade & summary.trigger & enabled_mask[:, None], axis=1
    ).astype(jnp.int32)
    return (regime_carry2, mrf_carry2, pt_carry2), wire, trig_counts, at_counts


def _chunk_ingest_counts(
    ext_times: jnp.ndarray,  # (S, W+L) extension times
    counts: jnp.ndarray,  # (T, S) cumulative bars applied through tick t
    window: int,
    interval_s: int,
) -> jnp.ndarray:
    """(T, 4) per-tick ``(appends, rewrites, gaps, drops)`` for the ingest
    digest. The drive only batches clean strictly-newer appends (anything
    else breaks the chunk back to the serial path), so rewrites/drops are
    identically zero here — matching what the serial classifier reads on
    the same stream. Gap bars are judged position-locally (a laid bar more
    than one whole bucket past its ring predecessor), exactly the serial
    rule, via one cumulative-sum pass over the extension columns."""
    S = ext_times.shape[0]
    laid = ext_times[:, window:]  # (S, L) — k-th laid bar per symbol
    prev = ext_times[:, window - 1 : -1]  # its ring predecessor
    gapflag = (laid >= 0) & (prev >= 0) & ((laid - prev) > interval_s)
    gcum = jnp.concatenate(
        [
            jnp.zeros((S, 1), jnp.int32),
            jnp.cumsum(gapflag.astype(jnp.int32), axis=1),
        ],
        axis=1,
    )  # (S, L+1): gap bars among the first k laid
    prev_counts = jnp.concatenate(
        [jnp.zeros((1, S), counts.dtype), counts[:-1]], axis=0
    )
    appends_t = jnp.sum(counts - prev_counts, axis=1).astype(jnp.float32)
    g_hi = jnp.take_along_axis(gcum, counts.T.astype(jnp.int32), axis=1)
    g_lo = jnp.take_along_axis(gcum, prev_counts.T.astype(jnp.int32), axis=1)
    gaps_t = jnp.sum(g_hi - g_lo, axis=0).astype(jnp.float32)
    zeros = jnp.zeros_like(appends_t)
    return jnp.stack([appends_t, zeros, gaps_t, zeros], axis=1)


def _chunk_ingest_blocks(
    times5_last: jnp.ndarray,  # (T, S) each tick's newest 5m bar time
    filled5: jnp.ndarray,  # (T, S)
    times15_last: jnp.ndarray,
    filled15: jnp.ndarray,
    ext5,
    ext15,
    counts5: jnp.ndarray,
    counts15: jnp.ndarray,
    inputs_seq: HostInputs,
    window: int,
) -> jnp.ndarray:
    """(T, INGEST_DIGEST_WIDTH) stacked ingest blocks — the same shared
    ``_ingest_interval_stats`` reductions the serial step runs, vmapped
    over the tick axis (exact integer ops → bit-identical blocks). Takes
    the per-tick (last-bar time, filled) arrays directly so BOTH
    precompute paths feed it: the vmapped path from its window views'
    last columns, the extension-invariant path from plain gathers (no
    (T, S, W) view needed)."""
    from binquant_tpu.engine.step import (
        FIFTEEN_MIN_S,
        FIVE_MIN_S,
        _ingest_interval_stats,
    )

    def stats(latest_seq, filled_seq, eval_ts_seq, interval_s):
        def one(latest, filled, tracked, eval_ts):
            return jnp.stack(
                _ingest_interval_stats(
                    latest, filled, tracked, eval_ts, interval_s
                )
            )

        return jax.vmap(one)(
            latest_seq, filled_seq, inputs_seq.tracked, eval_ts_seq
        )

    tracked_ct = jnp.sum(inputs_seq.tracked, axis=1).astype(jnp.float32)
    return jnp.concatenate(
        [
            tracked_ct[:, None],
            stats(times5_last, filled5, inputs_seq.timestamp5_s, FIVE_MIN_S),
            _chunk_ingest_counts(ext5[0], counts5, window, FIVE_MIN_S),
            stats(times15_last, filled15, inputs_seq.timestamp_s, FIFTEEN_MIN_S),
            _chunk_ingest_counts(ext15[0], counts15, window, FIFTEEN_MIN_S),
        ],
        axis=1,
    )


def _backtest_chunk_impl(
    ext5: tuple[jnp.ndarray, jnp.ndarray],
    ext15: tuple[jnp.ndarray, jnp.ndarray],
    counts5: jnp.ndarray,  # (T, S) int32 — bars applied through tick t
    counts15: jnp.ndarray,
    filled0: tuple[jnp.ndarray, jnp.ndarray],  # (S,) per interval
    carries: tuple[RegimeCarry, jnp.ndarray, jnp.ndarray],
    inputs_seq: HostInputs,  # (T, ...) leaves
    active: jnp.ndarray,  # (T,) bool
    momentum_ok: jnp.ndarray,  # (T,) bool
    policy_prev: tuple[jnp.ndarray, jnp.ndarray],
    cfg: ContextConfig = ContextConfig(),
    wire_enabled: tuple[str, ...] = tuple(sorted(LIVE_STRATEGIES)),
    window: int = 400,
    params=None,
    numeric_digest: bool = False,
    ingest_digest: bool = False,
    ext_invariant: bool = False,
):
    """T full-recompute ticks in one dispatch over the extended buffers.

    Returns ``(carries', (valid, regime), wires (T, L), fired_count (T,),
    (trig_counts, autotrade_counts) (T, N))``. Ticks whose fired count
    exceeds ``WIRE_MAX_FIRED`` must be re-driven serially by the caller
    (pre-chunk state stays the anchor — nothing here is donated).

    ``ext_invariant`` (static) selects the extension-invariant precompute
    (``_precompute_ext``) over the default vmapped-views one — governed
    by the gate-margin tolerance contract, never bit-pinned. The driver
    only routes chunks here whose ``btc_row`` is constant across ticks.
    """
    from binquant_tpu.enums import MarketRegimeCode

    sp = resolve_params(params)
    unsupported = set(wire_enabled) - BACKTEST_STRATEGIES
    assert not unsupported, (
        f"backtest backend cannot evaluate {sorted(unsupported)} — "
        "buffer-consuming dormant kernels run via the serial drives"
    )
    S = ext5[0].shape[0]
    L = wire_length(
        S, numeric_digest=numeric_digest, ingest_digest=ingest_digest
    )
    n_strat = len(STRATEGY_ORDER)
    range_code = jnp.int32(int(MarketRegimeCode.RANGE))
    trans_code = jnp.int32(int(MarketRegimeCode.TRANSITIONAL))

    if ext_invariant:
        from binquant_tpu.strategies.features import ext_gather

        last5 = (counts5 + (window - 1)).astype(jnp.int32)
        last15 = (counts15 + (window - 1)).astype(jnp.int32)
        times5_last = ext_gather(ext5[0], last5)
        times15_last = ext_gather(ext15[0], last15)
        filled5 = jnp.minimum(filled0[0][None, :] + counts5, window).astype(
            jnp.int32
        )
        filled15 = jnp.minimum(filled0[1][None, :] + counts15, window).astype(
            jnp.int32
        )
        pre = _precompute_ext(
            ext5, ext15, counts5, counts15, filled0, inputs_seq, sp,
            window, wire_enabled, times5_last, times15_last,
            filled5, filled15,
        )
    else:
        views5 = _window_views(*ext5, counts5, filled0[0], window)
        views15 = _window_views(*ext15, counts15, filled0[1], window)
        pre = jax.vmap(
            lambda b5, b15, inp: _precompute_one(b5, b15, inp, sp)
        )(views5, views15, inputs_seq)
        times5_last = views5.times[:, :, -1]
        times15_last = views15.times[:, :, -1]
        filled5 = views5.filled
        filled15 = views15.filled
    # ABP's heavy core is position-local and sort-based, so the T
    # overlapping per-tick tails collapse into ONE extended-series pass
    # (bit-exact; the dominant precompute cost otherwise). Skipped at
    # trace time when the strategy is disabled — its window guard must not
    # fire for a wire set that never evaluates it.
    if "activity_burst_pump" in wire_enabled:
        abp_pre = abp_core_batch(ext5[1], counts5, window, sp.abp)
    else:
        T = counts5.shape[0]
        zeros = jnp.zeros((T, S), jnp.float32)
        abp_pre = (jnp.zeros((T, S), bool), zeros, {})

    ing_seq = (
        _chunk_ingest_blocks(
            times5_last, filled5, times15_last, filled15,
            ext5, ext15, counts5, counts15, inputs_seq, window,
        )
        if ingest_digest
        else None
    )

    def body(carry, xs):
        regime_c, mrf_c, pt_c, prev_valid, prev_regime = carry
        pre_t, abp_t, inp, act, mok, ing_t = xs
        allow = (
            mok
            & prev_valid
            & ((prev_regime == range_code) | (prev_regime == trans_code))
        )
        inp = inp._replace(grid_policy_allows=allow)

        def live(op):
            rc, mc, pc = op
            (rc2, mc2, pc2), wire, tc, ac = _evaluate_tick(
                pre_t, abp_t, inp, rc, mc, pc, cfg, wire_enabled, sp,
                numeric_digest,
                ingest_block=ing_t,
            )
            return rc2, mc2, pc2, wire, tc, ac

        def idle(op):
            rc, mc, pc = op
            return (
                rc, mc, pc,
                jnp.zeros((L,), jnp.float32),
                jnp.zeros((n_strat,), jnp.int32),
                jnp.zeros((n_strat,), jnp.int32),
            )

        rc2, mc2, pc2, wire, tc, ac = jax.lax.cond(
            act, live, idle, (regime_c, mrf_c, pt_c)
        )
        valid = jnp.where(act, wire[0] > 0.5, prev_valid)
        regime = jnp.where(act, wire[1].astype(jnp.int32), prev_regime)
        return (rc2, mc2, pc2, valid, regime), (wire, tc, ac)

    regime_c, mrf_c, pt_c = carries
    (regime_c, mrf_c, pt_c, valid, regime), (wires, tcounts, acounts) = (
        jax.lax.scan(
            body,
            (regime_c, mrf_c, pt_c, policy_prev[0], policy_prev[1]),
            (pre, abp_pre, inputs_seq, active, momentum_ok, ing_seq),
        )
    )
    return (
        (regime_c, mrf_c, pt_c),
        (valid, regime),
        wires,
        wires[:, WIRE_FIRED_COUNT_OFF],
        (tcounts, acounts),
    )


backtest_chunk = partial(
    jax.jit,
    static_argnames=(
        "cfg", "wire_enabled", "window", "numeric_digest", "ingest_digest",
        "ext_invariant",
    ),
)(_backtest_chunk_impl)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "wire_enabled", "window", "with_fired_slots", "ext_invariant",
    ),
)
def backtest_chunk_sweep(
    ext5,
    ext15,
    counts5,
    counts15,
    filled0,
    carries,  # (P,)-batched leaves (RegimeCarry, mrf, pt)
    inputs_seq,
    active,
    momentum_ok,
    policy_prev,  # ((P,) bool, (P,) int32)
    cfg: ContextConfig = ContextConfig(),
    wire_enabled: tuple[str, ...] = tuple(sorted(LIVE_STRATEGIES)),
    window: int = 400,
    params=None,  # DynamicParams with (P,) float leaves on swept axes
    with_fired_slots: bool = True,
    ext_invariant: bool = False,
):
    """One dispatch scoring P strategy-parameter combos over the chunk.

    ``vmap`` over the params' dynamic (float) leaves + the per-combo scan
    carries; buffers, packs, symbol features and every other
    params-independent intermediate carries no batch dim and is computed
    ONCE. Returns ``(carries', policy', fired_count (P, T), trig_counts
    (P, T, N), autotrade_counts (P, T, N), fired_slots (P, T, 3, K))`` —
    the full wires are deliberately NOT returned (P × T × L would
    dominate memory; XLA dead-code-eliminates the per-combo emission
    payload and calibration gathers). ``fired_slots`` is the wire's
    compacted fired block sliced down to the three rows the outcome
    scorer joins on — (strategy_idx, row, direction), K =
    ``WIRE_MAX_FIRED`` slots, invalid slots -1 — so economic scoring
    (ISSUE 12) costs 3K floats per (combo, tick), not a wire.
    ``with_fired_slots=False`` (static — the scoring-off throughput
    arms) returns None there and restores the pre-scoring graph: nothing
    of the wire beyond the fired count survives DCE.
    """
    dyn_leaves, treedef = jax.tree_util.tree_flatten(params)
    axes = [0 if getattr(v, "ndim", 0) >= 1 else None for v in dyn_leaves]
    K = WIRE_MAX_FIRED
    off = WIRE_FIRED_COUNT_OFF

    def run_one(carries_one, policy_one, *leaves):
        p = jax.tree_util.tree_unflatten(treedef, leaves)
        carries2, policy2, wires, fired, (tc, ac) = _backtest_chunk_impl(
            ext5, ext15, counts5, counts15, filled0, carries_one,
            inputs_seq, active, momentum_ok, policy_one,
            cfg, wire_enabled, window, p,
            ext_invariant=ext_invariant,
        )
        if not with_fired_slots:
            return carries2, policy2, fired, tc, ac, None
        blocks = wires[:, off + 1 : off + 1 + 6 * K].reshape(
            wires.shape[0], 6, K
        )
        # rows 0/1/3 of the fired block: strategy_idx, row, direction
        slots = blocks[:, jnp.asarray((0, 1, 3)), :]
        return carries2, policy2, fired, tc, ac, slots

    return jax.vmap(run_one, in_axes=(0, 0, *axes))(
        carries, policy_prev, *dyn_leaves
    )
