"""Pallas rolling-quantile kernel: exact parity with the XLA path.

The TPU kernel (``ops/pallas_rolling.py``) replaces the windowed
gather+sort with a count-based selection; it must be BIT-IDENTICAL to
``rolling_quantile_tail`` (which itself is pandas-parity pinned in
tests/test_ops_parity.py) across NaN patterns, short inputs, ties, and
min_periods warm-up. Skipped off-TPU (the kernel is TPU-only by design;
``rolling_quantile_tail_auto`` falls back to XLA there).
"""

import jax
import numpy as np
import pytest

import jax.numpy as jnp

from binquant_tpu.ops.rolling import rolling_quantile_tail

tpu_only = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="pallas kernel is TPU-only"
)


def _cases():
    rng = np.random.default_rng(5)
    x = rng.random((37, 128)).astype(np.float32)
    x[3, :50] = np.nan  # leading NaN (the ring buffer's only NaN pattern)
    x[7, :] = np.nan  # all-NaN row
    x[11, -3:] = np.nan  # NaN inside the evaluated windows
    x[13, 10:20] = x[13, 0]  # ties
    return x


@tpu_only
@pytest.mark.parametrize("q", [0.5, 0.8, 0.92])
@pytest.mark.parametrize("num_out", [1, 4])
def test_kernel_matches_xla(q, num_out):
    from binquant_tpu.ops.pallas_rolling import rolling_quantile_tail_pallas

    x = jnp.asarray(_cases())
    ref = np.asarray(
        rolling_quantile_tail(x, 80, q, num_out=num_out, min_periods=20)
    )
    out = np.asarray(
        rolling_quantile_tail_pallas(x, 80, q, num_out=num_out, min_periods=20)
    )
    assert np.array_equal(np.isnan(ref), np.isnan(out))
    np.testing.assert_array_equal(
        np.nan_to_num(ref, nan=-9e9), np.nan_to_num(out, nan=-9e9)
    )


@tpu_only
def test_kernel_short_input_pads_like_xla():
    from binquant_tpu.ops.pallas_rolling import rolling_quantile_tail_pallas

    x = jnp.asarray(_cases()[:, :60])  # W < window + num_out - 1
    ref = np.asarray(rolling_quantile_tail(x, 80, 0.92, num_out=4, min_periods=20))
    out = np.asarray(
        rolling_quantile_tail_pallas(x, 80, 0.92, num_out=4, min_periods=20)
    )
    assert np.array_equal(np.isnan(ref), np.isnan(out))
    np.testing.assert_array_equal(
        np.nan_to_num(ref, nan=-9e9), np.nan_to_num(out, nan=-9e9)
    )


def test_auto_dispatch_always_correct(monkeypatch):
    """Whatever the backend, the auto path equals the XLA reference —
    with the flag ON, so the pallas branch is actually taken on TPU."""
    from binquant_tpu.ops.pallas_rolling import rolling_quantile_tail_auto

    monkeypatch.setenv("BQT_ENABLE_PALLAS", "1")
    monkeypatch.delenv("BQT_DISABLE_PALLAS", raising=False)
    x = jnp.asarray(_cases())
    ref = np.asarray(rolling_quantile_tail(x, 80, 0.92, num_out=4, min_periods=20))
    out = np.asarray(
        rolling_quantile_tail_auto(x, 80, 0.92, num_out=4, min_periods=20)
    )
    assert np.array_equal(np.isnan(ref), np.isnan(out))
    np.testing.assert_allclose(
        np.nan_to_num(ref, nan=-9e9), np.nan_to_num(out, nan=-9e9), rtol=1e-6
    )


def test_pallas_is_opt_in(monkeypatch):
    # default off (the fused XLA sort measured faster IN the tick step);
    # BQT_ENABLE_PALLAS turns it on, BQT_DISABLE_PALLAS always wins
    from binquant_tpu.ops import pallas_rolling

    monkeypatch.delenv("BQT_ENABLE_PALLAS", raising=False)
    monkeypatch.delenv("BQT_DISABLE_PALLAS", raising=False)
    assert not pallas_rolling.pallas_available()
    monkeypatch.setenv("BQT_ENABLE_PALLAS", "1")
    assert pallas_rolling.pallas_available() == (
        jax.default_backend() == "tpu"
    )
    monkeypatch.setenv("BQT_DISABLE_PALLAS", "1")
    assert not pallas_rolling.pallas_available()
