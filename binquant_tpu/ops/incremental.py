"""Incremental indicator state: O(1)-per-tick carries for the hot path.

VERDICT r5 measured the jit'd tick step as bytes-bound by construction:
every tick recomputed full 400-bar rolling windows for all symbols (~11.8 GB
of HBM traffic per tick for ~1.9 GFLOP). Most of the indicator set admits
carried state that advances with ONE new bar per symbol:

* **EWM/EMA** (``EwmCarry``) — the pandas ``adjust=False`` recursion
  ``y' = (1-a)·y + a·x`` seeded at the first valid sample, plus a
  positions-since-first-valid counter for ``min_periods`` gating. This is
  the exact recurrence the full-window matmul in :mod:`ops.rolling`
  closed-forms; the carried value differs from the windowed recompute only
  by the exponentially-forgotten pre-window prefix (``(1-a)^W`` — below
  f32 resolution at production spans × W=400).
* **Rolling sums** (``SumCarry``) — windowed sum + finite count, advanced
  by adding the entering sample and subtracting the leaving one (the
  leaver is still resident in the ring buffer at column ``-(window+1)``).
* **Rolling moments** (``MomentCarry``) — windowed Σ(x−c) and Σ(x−c)² around
  a per-symbol reference ``c`` (re-anchored whenever the window empties and
  on every full-recompute resync). Centering is what keeps f32
  sum-of-squares exact at BTC-scale prices: uncentered Σx² at 6.8e4² loses
  ~8% of a 20-bar variance to quantization; centered keeps it at ~1e-6.
* **Supertrend** (``SupertrendCarry``) — the band-ratchet + Wilder-ATR scan
  carry from :func:`ops.indicators.supertrend_from`, advanced one bar via
  the SAME step body the scan runs (one copy of the path-dependent
  recursion — see ``indicators._supertrend_step``).
* **Beta/corr** (``BetaCorrCarry``) — the five windowed sums behind
  :func:`ops.indicators.rolling_beta_corr`'s last value.
* **Order statistics** (``SortedCarry``) — a per-lane SORTED sliding
  window (finite values ascending, ``+inf`` sentinel padding) advanced by
  evict-one/insert-one merges: two O(window) gathers per bar instead of
  the full path's O(TAIL·window·log window) windowed sorts. The readouts
  (:func:`sorted_quantile` / :func:`sorted_median`) interpolate exactly
  like :func:`ops.rolling.rolling_quantile` — same rank clamps, same
  NaN-aware ``min_periods`` count — so a carry holding the same multiset
  as a window reads out bit-identically to sorting that window.

Every carry has ``*_init`` (from a full window — bit-identical to the
full-window kernels at the init tick, since both evaluate the same
expressions) and ``*_advance`` (one bar, O(1) bytes per symbol). Parity
against the full-window path is pinned in tests/test_ops_parity.py
(TestIncrementalOps); drift from f32 accumulation is bounded in production
by the engine's periodic full-recompute audit (io/pipeline.py) — and,
since ISSUE 7, *measured* there: every audit tick compares the carried
values against the fresh re-init per family BEFORE the resync overwrites
them (``engine/step.py measure_carry_drift`` → ``bqt_carry_drift{family}``
histograms + the ``BQT_DRIFT_TOL`` alarm), so accumulation residue,
sorted-window multiset divergence, and the supertrend forgotten-prefix
gap are production-visible numbers, not assumptions.

All carries are flat pytrees of (S,)/(S, k) arrays: they ride EngineState,
checkpoint with it, and shard over the symbol mesh by the existing
shape-based placement (parallel/mesh.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from binquant_tpu.utils import jsafe_div

__all__ = [
    "EwmCarry",
    "SumCarry",
    "MomentCarry",
    "SupertrendCarry",
    "BetaCorrCarry",
    "ewm_init",
    "ewm_advance",
    "ewm_value",
    "sum_init",
    "sum_advance",
    "sum_value",
    "sum_mean",
    "moment_init",
    "moment_advance",
    "moment_mean",
    "moment_var",
    "moment_std",
    "supertrend_init",
    "supertrend_advance",
    "beta_corr_init",
    "beta_corr_advance",
    "beta_corr_value",
    "empty_supertrend_carry",
    "empty_beta_corr_carry",
    "SortedCarry",
    "sorted_init",
    "sorted_advance",
    "sorted_quantile",
    "sorted_median",
]


def _fin(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.isfinite(x)


# ---------------------------------------------------------------------------
# EWM (pandas ewm(adjust=False).mean() recursion)
# ---------------------------------------------------------------------------


class EwmCarry(NamedTuple):
    """Carried EWM state per lane.

    ``rel`` counts positions since the first valid sample (-1 = none seen),
    matching ``ewm_mean_last``'s ``seen = rel + 1 >= min_periods`` gate.
    """

    mean: jnp.ndarray  # (...,) f32 — recursion value (0 before first valid)
    rel: jnp.ndarray  # (...,) int32 — positions since first valid, -1 none


def ewm_init(x: jnp.ndarray, alpha: float) -> EwmCarry:
    """Carry equivalent to running the recursion over the window ``x``
    (..., W): seeded from the SAME closed form :func:`ops.rolling.
    ewm_mean_last` evaluates (shared via ``ewm_last_state``), so the init
    tick is bit-identical to the full-window kernel by construction."""
    from binquant_tpu.ops.rolling import ewm_last_state

    mean, rel, any_valid = ewm_last_state(x, alpha)
    return EwmCarry(
        mean=jnp.where(any_valid, mean, 0.0).astype(jnp.float32),
        rel=jnp.where(any_valid, rel, -1).astype(jnp.int32),
    )


def ewm_advance(carry: EwmCarry, x: jnp.ndarray, alpha: float) -> EwmCarry:
    """One bar: ``y' = (1-a)·y + a·x`` (NaN contributes 0 and decays the
    carry, exactly the full path's zero-filled matmul semantics)."""
    started = carry.rel >= 0
    fin = _fin(x)
    xf = jnp.where(fin, x, 0.0).astype(jnp.float32)
    mean = jnp.where(started, (1.0 - alpha) * carry.mean + alpha * xf, xf)
    rel = jnp.where(started, carry.rel + 1, jnp.where(fin, 0, -1))
    return EwmCarry(
        mean=jnp.where(rel >= 0, mean, 0.0).astype(jnp.float32),
        rel=rel.astype(jnp.int32),
    )


def ewm_value(carry: EwmCarry, min_periods: int = 0) -> jnp.ndarray:
    """Readout with ``min_periods`` gating (NaN before warm-up)."""
    ok = (carry.rel >= 0) & (carry.rel + 1 >= max(min_periods, 1))
    return jnp.where(ok, carry.mean, jnp.nan)


# ---------------------------------------------------------------------------
# Rolling sum (NaN-aware windowed sum + finite count)
# ---------------------------------------------------------------------------


class SumCarry(NamedTuple):
    wsum: jnp.ndarray  # (...,) f32 — windowed sum over finite samples
    cnt: jnp.ndarray  # (...,) int32 — finite samples in window


def sum_init(x: jnp.ndarray, window: int) -> SumCarry:
    tail = x[..., -window:]
    m = _fin(tail)
    return SumCarry(
        wsum=jnp.sum(jnp.where(m, tail, 0.0), axis=-1).astype(jnp.float32),
        cnt=jnp.sum(m, axis=-1).astype(jnp.int32),
    )


def sum_advance(
    carry: SumCarry, x_new: jnp.ndarray, x_old: jnp.ndarray
) -> SumCarry:
    """Add the entering sample, subtract the one leaving the window
    (``x_old`` — the ring column at ``-(window+1)`` after the append)."""
    fn, fo = _fin(x_new), _fin(x_old)
    wsum = carry.wsum + jnp.where(fn, x_new, 0.0) - jnp.where(fo, x_old, 0.0)
    cnt = carry.cnt + fn.astype(jnp.int32) - fo.astype(jnp.int32)
    # windows that empty out shed any f32 residue from the add/sub stream
    wsum = jnp.where(cnt == 0, 0.0, wsum)
    return SumCarry(wsum=wsum.astype(jnp.float32), cnt=cnt)


def sum_value(
    carry: SumCarry, window: int, min_periods: int | None = None
) -> jnp.ndarray:
    mp = window if min_periods is None else min_periods
    return jnp.where(carry.cnt >= mp, carry.wsum, jnp.nan)


def sum_mean(
    carry: SumCarry, window: int, min_periods: int | None = None
) -> jnp.ndarray:
    mp = max(window if min_periods is None else min_periods, 1)
    return jnp.where(
        carry.cnt >= mp, carry.wsum / jnp.maximum(carry.cnt, 1), jnp.nan
    )


# ---------------------------------------------------------------------------
# Rolling moments (mean/std/var around a carried center)
# ---------------------------------------------------------------------------


class MomentCarry(NamedTuple):
    """Windowed Σ(x−c), Σ(x−c)² around a per-lane reference ``c``.

    ``c`` is anchored at init (window nan-mean) and re-anchored whenever the
    window empties; within an epoch it is constant, so every sample's
    centered contribution is added and later subtracted as the SAME f32
    value — drift reduces to accumulation-order noise, bounded by the
    engine's periodic full-recompute resync.
    """

    center: jnp.ndarray  # (...,) f32
    wsum: jnp.ndarray  # (...,) f32 — Σ(x−c) over finite window samples
    wsq: jnp.ndarray  # (...,) f32 — Σ(x−c)²
    cnt: jnp.ndarray  # (...,) int32


def moment_init(x: jnp.ndarray, window: int) -> MomentCarry:
    tail = x[..., -window:]
    m = _fin(tail)
    cnt = jnp.sum(m, axis=-1)
    center = jnp.sum(jnp.where(m, tail, 0.0), axis=-1) / jnp.maximum(cnt, 1)
    center = jnp.where(cnt > 0, center, 0.0)
    d = jnp.where(m, tail - center[..., None], 0.0)
    return MomentCarry(
        center=center.astype(jnp.float32),
        wsum=jnp.sum(d, axis=-1).astype(jnp.float32),
        wsq=jnp.sum(d * d, axis=-1).astype(jnp.float32),
        cnt=cnt.astype(jnp.int32),
    )


def moment_advance(
    carry: MomentCarry, x_new: jnp.ndarray, x_old: jnp.ndarray
) -> MomentCarry:
    fn, fo = _fin(x_new), _fin(x_old)
    center = jnp.where((carry.cnt == 0) & fn, x_new, carry.center)
    dn = jnp.where(fn, x_new - center, 0.0)
    do = jnp.where(fo, x_old - center, 0.0)
    cnt = carry.cnt + fn.astype(jnp.int32) - fo.astype(jnp.int32)
    wsum = carry.wsum + dn - do
    wsq = carry.wsq + dn * dn - do * do
    empty = cnt == 0
    return MomentCarry(
        center=center.astype(jnp.float32),
        wsum=jnp.where(empty, 0.0, wsum).astype(jnp.float32),
        wsq=jnp.where(empty, 0.0, jnp.maximum(wsq, 0.0)).astype(jnp.float32),
        cnt=cnt,
    )


def moment_mean(
    carry: MomentCarry, window: int, min_periods: int | None = None
) -> jnp.ndarray:
    mp = max(window if min_periods is None else min_periods, 1)
    mean = carry.center + carry.wsum / jnp.maximum(carry.cnt, 1)
    return jnp.where(carry.cnt >= mp, mean, jnp.nan)


def moment_var(
    carry: MomentCarry,
    window: int,
    min_periods: int | None = None,
    ddof: int = 1,
) -> jnp.ndarray:
    """Same algebra as ``rolling_std_last``: Σ(x−x̄)² = Σd² − (Σd)²/n."""
    mp = max(window if min_periods is None else min_periods, 1)
    cnt = carry.cnt
    sq = carry.wsq - carry.wsum * carry.wsum / jnp.maximum(cnt, 1)
    var = jnp.maximum(sq, 0.0) / jnp.maximum(cnt - ddof, 1)
    ok = (cnt >= mp) & (cnt > ddof)
    return jnp.where(ok, var, jnp.nan)


def moment_std(
    carry: MomentCarry,
    window: int,
    min_periods: int | None = None,
    ddof: int = 1,
) -> jnp.ndarray:
    return jnp.sqrt(moment_var(carry, window, min_periods, ddof))


# ---------------------------------------------------------------------------
# Supertrend (band ratchet + Wilder ATR — path-dependent scan carry)
# ---------------------------------------------------------------------------


class SupertrendCarry(NamedTuple):
    """The scan carry of :func:`ops.indicators.supertrend_from`, reshaped to
    the lane batch. ``advance`` runs the SAME step body the scan runs."""

    atr: jnp.ndarray  # (...,) f32 Wilder-ATR recursion value
    n_seen: jnp.ndarray  # (...,) int32 bars consumed since series start
    final_upper: jnp.ndarray  # (...,) f32 ratcheted upper band
    final_lower: jnp.ndarray  # (...,) f32 ratcheted lower band
    direction: jnp.ndarray  # (...,) f32 +1/-1
    prev_close: jnp.ndarray  # (...,) f32


def empty_supertrend_carry(num_symbols: int) -> SupertrendCarry:
    """The scan's initial carry at (S,) batch — delegated to
    :func:`ops.indicators.supertrend_scan_init` so the empty state can
    never drift from the recursion's actual seed."""
    from binquant_tpu.ops.indicators import supertrend_scan_init

    return SupertrendCarry(*supertrend_scan_init((num_symbols,)))


def supertrend_init(
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    window: int = 10,
    multiplier: float = 3.0,
    start: jnp.ndarray | None = None,
) -> SupertrendCarry:
    """Run the full-window scan once and keep its final carry: the series
    starts at each lane's first finite bar, exactly like
    :func:`ops.indicators.supertrend` — or at an explicit per-lane
    ``start`` (the dropna'd-frame seed strategy consumers use,
    ``strategies/dormant.py:supertrend_swing_reversal``)."""
    from binquant_tpu.ops.indicators import _supertrend_scan

    W = close.shape[-1]
    if start is None:
        finite = _fin(high) & _fin(low) & _fin(close)
        start = jnp.min(
            jnp.where(finite, jnp.arange(W, dtype=jnp.int32), W), axis=-1
        )
    carry, _, _ = _supertrend_scan(high, low, close, start, window, multiplier)
    return SupertrendCarry(*carry)


def supertrend_advance(
    carry: SupertrendCarry,
    high: jnp.ndarray,
    low: jnp.ndarray,
    close: jnp.ndarray,
    window: int = 10,
    multiplier: float = 3.0,
    active: jnp.ndarray | bool = True,
) -> tuple[SupertrendCarry, jnp.ndarray, jnp.ndarray]:
    """One bar through the shared step body → (carry', line, direction).
    Outputs are NaN until the ATR recursion is warm (n_seen >= window), the
    same validity the scan emits."""
    from binquant_tpu.ops.indicators import _supertrend_step

    active = jnp.broadcast_to(jnp.asarray(active), jnp.shape(close))
    new_carry, line, dirn = _supertrend_step(
        tuple(carry), high, low, close, active, window, multiplier
    )
    return SupertrendCarry(*new_carry), line, dirn


# ---------------------------------------------------------------------------
# Rolling beta / correlation vs a benchmark (the 5 windowed sums)
# ---------------------------------------------------------------------------


class BetaCorrCarry(NamedTuple):
    sx: jnp.ndarray
    sy: jnp.ndarray
    sxy: jnp.ndarray
    sxx: jnp.ndarray
    syy: jnp.ndarray
    cnt: jnp.ndarray  # int32 — both-finite pairs in window


def empty_beta_corr_carry(num_symbols: int) -> BetaCorrCarry:
    """All-zero sums/count — what ``beta_corr_init`` yields on an empty
    window (readouts report the not-enough-pairs NaN until seeded)."""
    z = jnp.zeros((num_symbols,), jnp.float32)
    return BetaCorrCarry(
        sx=z, sy=z, sxy=z, sxx=z, syy=z,
        cnt=jnp.zeros((num_symbols,), jnp.int32),
    )


def _pairs(x: jnp.ndarray, y: jnp.ndarray):
    both = _fin(x) & _fin(y)
    return both, jnp.where(both, x, 0.0), jnp.where(both, y, 0.0)


def beta_corr_init(
    x: jnp.ndarray, y: jnp.ndarray, window: int = 50
) -> BetaCorrCarry:
    bx = jnp.broadcast_to(y, x.shape)
    both, xf, yf = _pairs(x[..., -window:], bx[..., -window:])
    return BetaCorrCarry(
        sx=jnp.sum(xf, axis=-1).astype(jnp.float32),
        sy=jnp.sum(yf, axis=-1).astype(jnp.float32),
        sxy=jnp.sum(xf * yf, axis=-1).astype(jnp.float32),
        sxx=jnp.sum(xf * xf, axis=-1).astype(jnp.float32),
        syy=jnp.sum(yf * yf, axis=-1).astype(jnp.float32),
        cnt=jnp.sum(both, axis=-1).astype(jnp.int32),
    )


def beta_corr_advance(
    carry: BetaCorrCarry,
    x_new: jnp.ndarray,
    y_new: jnp.ndarray,
    x_old: jnp.ndarray,
    y_old: jnp.ndarray,
) -> BetaCorrCarry:
    fn, xn, yn = _pairs(x_new, y_new)
    fo, xo, yo = _pairs(x_old, y_old)
    cnt = carry.cnt + fn.astype(jnp.int32) - fo.astype(jnp.int32)
    z = cnt == 0

    def upd(s, add, sub):
        return jnp.where(z, 0.0, s + add - sub).astype(jnp.float32)

    return BetaCorrCarry(
        sx=upd(carry.sx, xn, xo),
        sy=upd(carry.sy, yn, yo),
        sxy=upd(carry.sxy, xn * yn, xo * yo),
        sxx=upd(carry.sxx, xn * xn, xo * xo),
        syy=upd(carry.syy, yn * yn, yo * yo),
        cnt=cnt,
    )


def beta_corr_value(
    carry: BetaCorrCarry, window: int = 50
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(beta, corr) matching :func:`ops.indicators.rolling_beta_corr`'s
    last values (min_periods = window, ddof=0 variance)."""
    n = jnp.maximum(carry.cnt, 1)
    mx, my = carry.sx / n, carry.sy / n
    cov = carry.sxy / n - mx * my
    var_b = carry.syy / n - my * my
    vx = jnp.maximum(carry.sxx / n - mx * mx, 0.0)
    beta = jsafe_div(cov, var_b)
    corr = jnp.clip(
        jsafe_div(cov, jnp.sqrt(jnp.maximum(vx * var_b, 0.0))), -1.0, 1.0
    )
    ok = carry.cnt >= window
    return jnp.where(ok, beta, jnp.nan), jnp.where(ok, corr, jnp.nan)


# ---------------------------------------------------------------------------
# Sorted sliding window (rolling median / quantile order statistics)
# ---------------------------------------------------------------------------


class SortedCarry(NamedTuple):
    """Per-lane sorted sliding window for O(window)-merge order statistics.

    ``sorted`` holds the window's finite values ascending with ``+inf``
    sentinels in the remaining slots (exactly how the full-window kernels
    sort NaN to the end); ``cnt`` is the finite count the ``min_periods``
    gate and the interpolation rank read. Eviction is by VALUE: the caller
    must pass the bit-identical f32 that entered ``window`` advances ago
    (a ring-buffer column or a companion history ring provides it — both
    return the stored bits unchanged). An evict value that is no longer
    present (carry drifted) silently removes the nearest >= entry; the
    engine's periodic full-recompute resync bounds that failure mode the
    same way it bounds f32 accumulation drift in the sum carries.
    """

    sorted: jnp.ndarray  # (..., window) f32 ascending, +inf padding
    cnt: jnp.ndarray  # (...,) int32 finite values in window


def sorted_init(x: jnp.ndarray, window: int) -> SortedCarry:
    """Carry from the trailing ``window`` samples of ``x`` (..., W>=window):
    the same sort the full-window kernels run, so readouts at the init tick
    are bit-identical by construction."""
    tail = x[..., -window:]
    m = _fin(tail)
    return SortedCarry(
        sorted=jnp.sort(jnp.where(m, tail, jnp.inf), axis=-1).astype(
            jnp.float32
        ),
        cnt=jnp.sum(m, axis=-1).astype(jnp.int32),
    )


def sorted_advance(
    carry: SortedCarry, x_new: jnp.ndarray, x_old: jnp.ndarray
) -> SortedCarry:
    """One bar: remove ``x_old`` (the sample leaving the window), insert
    ``x_new`` — two rank computations + two O(window) gathers per lane.
    Non-finite samples map to the ``+inf`` sentinel on both sides, so a
    NaN entering or leaving shifts only the padding region and ``cnt``.
    """
    s = carry.sorted
    window = s.shape[-1]
    fn, fo = _fin(x_new), _fin(x_old)
    xo = jnp.where(fo, x_old, jnp.inf).astype(jnp.float32)
    xn = jnp.where(fn, x_new, jnp.inf).astype(jnp.float32)

    idx = jnp.arange(window)
    # evict: first index holding a value >= x_old is x_old's slot (it is
    # present by the carry invariant); shift everything after it left.
    e = jnp.sum(s < xo[..., None], axis=-1, keepdims=True)  # (..., 1)
    t = jnp.take_along_axis(
        s, jnp.minimum(idx + (idx >= e), window - 1), axis=-1
    )  # (..., window); only [0, window-2] meaningful after removal
    # insert: rank among the window-1 survivors, then shift right from it.
    i = jnp.sum(t[..., : window - 1] < xn[..., None], axis=-1, keepdims=True)
    u = jnp.where(
        idx == i,
        xn[..., None],
        jnp.take_along_axis(t, idx - (idx > i), axis=-1),
    )
    cnt = carry.cnt + fn.astype(jnp.int32) - fo.astype(jnp.int32)
    return SortedCarry(sorted=u.astype(jnp.float32), cnt=cnt)


def sorted_quantile(
    carry: SortedCarry, q: float, min_periods: int = 1
) -> jnp.ndarray:
    """Linear-interpolated quantile at rank ``q·(cnt−1)`` — the SAME
    clamps/indexing as :func:`ops.rolling.rolling_quantile` (and the
    inline LSP sort it mirrors), so a carry holding a window's multiset
    reads out bit-identically to sorting that window."""
    s = carry.sorted
    window = s.shape[-1]
    cnt = carry.cnt
    rank = q * (cnt - 1.0)
    lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, window - 1)
    hi = jnp.clip(lo + 1, 0, window - 1)
    frac = rank - lo.astype(s.dtype)
    v_lo = jnp.take_along_axis(s, lo[..., None], axis=-1)[..., 0]
    v_hi = jnp.take_along_axis(
        s, jnp.minimum(hi, jnp.maximum(cnt - 1, 0))[..., None], axis=-1
    )[..., 0]
    out = v_lo + (v_hi - v_lo) * frac
    return jnp.where(cnt >= max(min_periods, 1), out, jnp.nan)


def sorted_median(carry: SortedCarry, min_periods: int = 1) -> jnp.ndarray:
    return sorted_quantile(carry, 0.5, min_periods)
