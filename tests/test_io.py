"""Host I/O edges: telegram sink, autotrade gates, ws parser, calibrator.

Mirrors the reference's seam discipline (tests/conftest.py:34-49 patches
BinbotApi; fakes over fakes-of-the-network) — here the seams are injectable
transports/sessions instead of monkeypatching.
"""

import asyncio
import json

import numpy as np
import pytest

from binquant_tpu.io.autotrade import Autotrade, AutotradeConsumer
from binquant_tpu.io.binbot import BinbotApi
from binquant_tpu.io.leverage import LeverageCalibrator
from binquant_tpu.io.telegram import TelegramConsumer
from binquant_tpu.io.websocket import (
    KlinesConnector,
    filter_fiat_symbols,
    parse_binance_kline_frame,
)
from binquant_tpu.engine.buffer import SymbolRegistry
from binquant_tpu.enums import MarketRegimeCode
from binquant_tpu.schemas import (
    AutotradeSettingsSchema,
    BotBase,
    HABollinguerSpread,
    SignalsConsumer,
    SymbolModel,
    TestAutotradeSettingsSchema,
)
from tests.test_regime_routing_scoring import mk_context


# ---------------------------------------------------------------------------
# Telegram
# ---------------------------------------------------------------------------


def make_consumer(sent):
    async def transport(chat_id, text):
        sent.append(text)

    return TelegramConsumer(token="", chat_id="c", transport=transport)


SIGNAL_MSG = """
    - [test] <strong>#mean_reversion_fade algorithm</strong> #BTCUSDT
    - Action: LONG ENTRY
    - Current price: 100.5
    - Strategy: long
    - Autotrade route: long_autotrade_allowed
    - Autotrade is enabled
"""


class TestTelegram:
    def test_dedupe_within_cooldown(self):
        async def run():
            sent = []
            consumer = make_consumer(sent)
            consumer._min_send_interval_seconds = 0
            t1 = consumer.dispatch_signal(SIGNAL_MSG)
            assert t1 is not None
            await t1
            # identical payload within 900s -> dropped
            assert consumer.dispatch_signal(SIGNAL_MSG) is None
            # different action -> new key, sent
            other = SIGNAL_MSG.replace("LONG ENTRY", "SHORT ENTRY")
            t2 = consumer.dispatch_signal(other)
            assert t2 is not None
            await t2
            assert len(sent) == 2

        asyncio.run(run())

    def test_sanitize_preserves_whitelist(self):
        consumer = make_consumer([])
        out = consumer._sanitize_html(
            "<strong>#x</strong> <script>evil()</script> RSI &lt; 30 "
            "<a href='https://x.y/z'>link</a>"
        )
        assert "<strong>#x</strong>" in out
        assert "&lt;script&gt;" in out
        assert "RSI &lt; 30" in out
        assert '<a href="https://x.y/z">link</a>' in out

    def test_disabled_consumer_never_sends(self):
        consumer = TelegramConsumer(token="", chat_id="c", is_enabled=False)
        assert consumer.dispatch_signal(SIGNAL_MSG) is None


# ---------------------------------------------------------------------------
# Websocket parsing
# ---------------------------------------------------------------------------


class TestWsParsing:
    def test_closed_kline_parsed_with_extended_fields(self):
        frame = json.dumps(
            {
                "e": "kline",
                "k": {
                    "s": "BTCUSDT", "x": True, "t": 1700000000000,
                    "T": 1700000899999, "o": "1.0", "h": "2.0", "l": "0.5",
                    "c": "1.5", "v": "10", "q": "15", "n": 42, "V": "6", "Q": "9",
                },
            }
        )
        out = parse_binance_kline_frame(frame)
        assert out["symbol"] == "BTCUSDT"
        assert out["quote_asset_volume"] == 15.0
        assert out["number_of_trades"] == 42.0
        assert out["taker_buy_base_volume"] == 6.0

    def test_open_candle_and_noise_dropped(self):
        open_frame = json.dumps(
            {"e": "kline", "k": {"s": "BTCUSDT", "x": False, "t": 1, "T": 2,
                                 "o": "1", "h": "1", "l": "1", "c": "1", "v": "1"}}
        )
        assert parse_binance_kline_frame(open_frame) is None
        assert parse_binance_kline_frame('{"e":"depthUpdate"}') is None
        assert parse_binance_kline_frame("not json{") is None

    def test_symbol_chunking_dual_interval(self):
        symbols = [SymbolModel(id=f"S{i}USDT") for i in range(450)]
        conn = KlinesConnector(
            asyncio.Queue(), symbols, connect=lambda *_: None,
            max_markets_per_client=400,
        )
        chunks = conn._chunks()
        # 200 symbols/client x 2 intervals = 400 streams per connection
        assert [len(c) for c in chunks] == [400, 400, 100]
        assert chunks[0][0] == "s0usdt@kline_5m"
        assert chunks[0][1] == "s0usdt@kline_15m"
        # every symbol carries BOTH intervals
        all_streams = [st for c in chunks for st in c]
        assert "s37usdt@kline_5m" in all_streams
        assert "s37usdt@kline_15m" in all_streams

    def test_fiat_filter(self):
        symbols = [
            SymbolModel(id="BTCUSDT"),
            SymbolModel(id="USDTTRY"),
            SymbolModel(id="USDCUSDT"),
            SymbolModel(id="ETHUSDT", active=False),
        ]
        kept = [s.id for s in filter_fiat_symbols(symbols)]
        assert kept == ["BTCUSDT"]


# ---------------------------------------------------------------------------
# Autotrade gate chain (fake binbot session)
# ---------------------------------------------------------------------------


class FakeResp:
    def __init__(self, payload, status_code=200):
        self._payload = payload
        self.status_code = status_code
        self.text = json.dumps(payload)

    def json(self):
        return self._payload


class FakeSession:
    """Scriptable binbot backend."""

    def __init__(self):
        self.calls = []
        self.active_pairs = []
        self.paper_pairs = []
        self.grid_ladders = []
        self.balance = 1000.0
        self.excluded = []
        self.created = []
        self.activated = []
        self.activation_error = False

    def request(self, method, url, **kwargs):
        self.calls.append((method, url, kwargs.get("json")))
        if "available-fiat" in url:
            return FakeResp({"data": {"amount": self.balance}})
        if "active-pairs/paper_trading" in url:
            return FakeResp({"data": self.paper_pairs})
        if "active-pairs" in url:
            return FakeResp({"data": self.active_pairs})
        if "excluded" in url:
            return FakeResp({"data": self.excluded})
        if "grid-ladders/active" in url:
            return FakeResp({"data": self.grid_ladders})
        if "grid-ladders/calculate" in url:
            return FakeResp({"data": {"levels": [1, 2, 3]}})
        if url.endswith("/grid-ladders") and method == "POST":
            self.created.append(("grid", kwargs.get("json")))
            return FakeResp({"data": {"ok": True}})
        if "/symbol/" in url and method == "GET":
            sym = url.rsplit("/", 1)[-1]
            return FakeResp({"data": {"id": sym, "quote_asset": "USDT"}})
        if ("/bot" in url or "paper-trading" in url) and method == "POST" and "errors" not in url:
            self.created.append(("bot", kwargs.get("json")))
            return FakeResp(
                {"message": "ok", "error": 0,
                 "data": {"pair": kwargs["json"]["pair"],
                          "id": "11111111-1111-1111-1111-111111111111"}}
            )
        if "activate" in url:
            if self.activation_error:
                return FakeResp({"message": "boom", "error": 1, "data": None})
            self.activated.append(url)
            return FakeResp(
                {"message": "ok", "error": 0,
                 "data": {"pair": "BTCUSDT", "status": "active"}}
            )
        if "deactivate" in url or "errors" in url or "clean-margin-short" in url:
            return FakeResp({"data": {}})
        return FakeResp({"data": {}})

    def get(self, url, params=None):
        return self.request("GET", url, params=params)


def make_at_consumer(session=None, autotrade=True, exchange="binance"):
    session = session or FakeSession()
    api = BinbotApi("http://fake", session=session)
    settings = AutotradeSettingsSchema(
        autotrade=autotrade, exchange_id=exchange, market_type="spot"
    )
    test_settings = TestAutotradeSettingsSchema(autotrade=False)
    consumer = AutotradeConsumer(
        autotrade_settings=settings,
        active_test_bots=[],
        all_symbols=[SymbolModel(id="BTCUSDT")],
        test_autotrade_settings=test_settings,
        active_grid_ladders=[],
        binbot_api=api,
    )
    return consumer, session


def make_signal(autotrade=True, pair="BTCUSDT", name="mean_reversion_fade"):
    return SignalsConsumer(
        autotrade=autotrade,
        current_price=100.0,
        direction="LONG",
        bot_params=BotBase(pair=pair, name=name, market_type="spot"),
        bb_spreads=HABollinguerSpread(bb_high=105, bb_mid=100, bb_low=95),
    )


class TestAutotradeGates:
    def test_full_path_creates_and_activates(self):
        consumer, session = make_at_consumer()
        asyncio.run(consumer.process_autotrade_restrictions(make_signal()))
        kinds = [k for k, _ in session.created]
        assert kinds == ["bot"]
        assert session.activated
        payload = session.created[0][1]
        # BB-spread-derived stop loss: whole spread ~9.52% in (2,20)
        assert 2 < payload["stop_loss"] < 20

    def test_insufficient_balance_blocks(self):
        consumer, session = make_at_consumer()
        session.balance = 1.0
        asyncio.run(consumer.process_autotrade_restrictions(make_signal()))
        assert session.created == []

    def test_grid_only_policy_blocks(self):
        from binquant_tpu.regime.grid_policy import GridOnlyPolicy

        consumer, session = make_at_consumer()
        consumer.grid_only_policy = GridOnlyPolicy.active(
            direction="toward_range", source="x", latest=0.4, previous=0.5
        )
        asyncio.run(consumer.process_autotrade_restrictions(make_signal()))
        assert session.created == []

    def test_duplicate_bot_blocks(self):
        consumer, session = make_at_consumer()
        session.active_pairs = ["BTCUSDT"]
        asyncio.run(consumer.process_autotrade_restrictions(make_signal()))
        assert session.created == []

    def test_activation_failure_cleans_up(self):
        consumer, session = make_at_consumer()
        session.activation_error = True
        from binquant_tpu.exceptions import AutotradeError

        with pytest.raises(AutotradeError):
            asyncio.run(consumer.process_autotrade_restrictions(make_signal()))
        # compensating deactivate happened
        assert any("deactivate" in url for _, url, _ in session.calls)

    def test_excluded_symbol_skipped(self):
        consumer, session = make_at_consumer()
        session.excluded = ["BTCUSDT"]
        asyncio.run(consumer.process_autotrade_restrictions(make_signal()))
        assert session.created == []

    def test_grid_deployment_cooldown(self):
        from datetime import datetime, timezone

        UTC = timezone.utc  # datetime.UTC alias (3.11+) for py3.10
        from binquant_tpu.schemas import GridDeploymentRequest, SignalKind

        consumer, session = make_at_consumer()
        grid = GridDeploymentRequest(
            symbol="BTCUSDT", fiat="USDT", exchange="binance",
            market_type="spot", algorithm_name="grid_ladder",
            generated_at=datetime.now(UTC),
            range_low=95, range_high=105, breakout_low=94, breakout_high=106,
            total_margin=10, level_count=7,
            allocation_pct=60.0, cash_reserve_pct=40.0,
        )
        sig = SignalsConsumer(
            signal_kind=SignalKind.grid_deploy, direction="grid",
            autotrade=True, current_price=100.0, grid_params=grid,
        )
        asyncio.run(consumer.process_autotrade_restrictions(sig))
        assert [k for k, _ in session.created] == ["grid"]
        # immediate retry within 1h cooldown -> skipped
        asyncio.run(consumer.process_autotrade_restrictions(sig))
        assert [k for k, _ in session.created] == ["grid"]


class TestAutotradeOverrides:
    def test_signal_overrides_beat_bb_derived_values(self):
        session = FakeSession()
        api = BinbotApi("http://fake", session=session)
        settings = AutotradeSettingsSchema(exchange_id="binance", autotrade=True)
        autotrade = Autotrade(
            pair="BTCUSDT", settings=settings,
            algorithm_name="mean_reversion_fade", binbot_api=api,
            db_collection_name="bots",
        )
        sig = make_signal()
        sig.bot_params.stop_loss = 7.77  # explicit override
        asyncio.run(autotrade.activate_autotrade(sig))
        payload = session.created[0][1]
        assert payload["stop_loss"] == 7.77  # override preserved
        assert payload["cooldown"] == 360


# ---------------------------------------------------------------------------
# Leverage calibrator
# ---------------------------------------------------------------------------


class TestLeverageCalibrator:
    def test_ladder_and_diffing(self):
        session = FakeSession()
        api = BinbotApi("http://fake", session=session)
        cal = LeverageCalibrator(api, "kucoin")
        reg = SymbolRegistry(6)
        for s in ["AUSDT", "BUSDT", "CUSDT"]:
            reg.add(s)
        ctx = mk_context(n=6, market_regime=np.int32(MarketRegimeCode.RANGE))
        rows = [
            SymbolModel(id="AUSDT", futures_leverage=1),
            SymbolModel(id="BUSDT", futures_leverage=2),
            SymbolModel(id="CUSDT", futures_leverage=1),
        ]
        out = cal.calibrate_all(ctx, reg, rows)
        # RANGE -> target 2x; A and C change, B already 2x
        assert out["applied"] == 2
        assert out["no_change"] == 1
        assert rows[0].futures_leverage == 2

    def test_defensive_regime_forces_1x(self):
        cal = LeverageCalibrator(
            BinbotApi("http://f", session=FakeSession()), "kucoin"
        )
        assert cal.target_leverage(10.0, 0.01, int(MarketRegimeCode.HIGH_STRESS), 0.1, 1.0) == 1
        assert cal.target_leverage(10.0, 0.01, int(MarketRegimeCode.TREND_UP), 0.1, 1.0) == 3
        assert cal.target_leverage(10.0, 0.05, int(MarketRegimeCode.TREND_UP), 0.1, 1.0) == 1  # spiky
        assert cal.target_leverage(600.0, 0.01, int(MarketRegimeCode.TREND_UP), 0.1, 1.0) == 1  # expensive
        assert cal.target_leverage(10.0, 0.01, int(MarketRegimeCode.RANGE), 0.8, 1.0) == 1  # stressed
