"""Benchmark: full-suite tick latency through the PRODUCTION engine.

Drives the real ``SignalEngine.process_tick`` (batcher drain → jit'd step
→ pipelined wire fetch → emission sinks) at the north-star scale: 2000
symbols × 400-bar windows on one chip (BASELINE.json: p99 < 50 ms @ 1 s
ticks). This is NOT a bespoke loop around the jit'd step — the measured
path is byte-for-byte the one ``main.py``'s consume_loop runs, and the
quoted percentiles come from the engine's own ``LatencyTracker``
(``tick_total``). Prints ONE JSON line:

    {"metric": "tick_p99_ms", "value": N, "unit": "ms", "vs_baseline": R}

``vs_baseline`` is the target budget ratio 50ms/value (>1 beats the
north-star; the reference itself is O(100ms–1s) *per symbol* serial —
SURVEY.md §6 — so any sub-50ms full-batch tick is ≥4 orders of magnitude
over the reference pipeline).

Three measurement phases, all through ``process_tick``:

* **pipelined back-to-back** (headline): ``pipeline_depth`` deep, ticks
  issued with no pause — steady-state per-tick wall time of the
  production loop (dispatch i + emit tick i-depth whose wire already
  landed). Depth 6 covers a ~100 ms tunneled-device RTT at back-to-back
  cadence; a local chip needs the live default of 1.
* **paced depth-1** (the live configuration): ``pipeline_depth=1`` with a
  pause between ticks, as main.py runs at 1 s cadence — the wire lands
  during the pause, so this is the truest production number.
* **serial e2e** (``pipeline_depth=0``): dispatch + same-tick wire fetch,
  paying the full host↔device round trip — the upper bound.

``--smoke`` runs tiny shapes for CI/CPU sanity. The five BASELINE.json
configs map to: ``--config1`` (single-symbol coinrule, per-symbol pandas
reference path), ``--config2`` (batched SMA/EMA/RSI over the 100-symbol
replay fixture), the default run (configs #3+#5: full strategy suite,
2000 symbols, end-to-end Signal emission at the live cadence), and
``--config4`` (context scoring × 4 timeframes).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

import numpy as np


def _seed_engine(num_symbols: int, window: int, depth: int,
                 incremental: bool | None = None):
    """A production SignalEngine (stub network sinks) with full windows."""
    import jax

    from binquant_tpu.engine.buffer import NUM_FIELDS, Field, apply_updates
    from binquant_tpu.io.replay import make_stub_engine

    rng = np.random.default_rng(7)
    engine = make_stub_engine(
        capacity=num_symbols, window=window, pipeline_depth=depth,
        incremental=incremental,
        donate=False if incremental is False else None,
        delivery=False,
    )
    names = ["BTCUSDT"] + [f"S{i:04d}USDT" for i in range(1, num_symbols)]
    rows_all = engine.registry.rows_for(names)
    assert int(rows_all[0]) == engine.registry.row_of("BTCUSDT")

    t0 = 1_753_000_200
    px = 20.0 + rng.random(num_symbols).astype(np.float32) * 100

    def make_updates(ts_s: int, px: np.ndarray, duration_s: int):
        rows = np.arange(num_symbols, dtype=np.int32)
        ts = np.full(num_symbols, ts_s, dtype=np.int32)
        closes = px * (1 + rng.normal(0, 0.004, num_symbols))
        vals = np.zeros((num_symbols, NUM_FIELDS), dtype=np.float32)
        vals[:, Field.OPEN] = px
        vals[:, Field.CLOSE] = closes
        vals[:, Field.HIGH] = np.maximum(px, closes) * 1.002
        vals[:, Field.LOW] = np.minimum(px, closes) * 0.998
        vals[:, Field.VOLUME] = np.abs(rng.normal(1000, 150, num_symbols))
        vals[:, Field.QUOTE_VOLUME] = vals[:, Field.VOLUME] * closes
        vals[:, Field.NUM_TRADES] = 150
        vals[:, Field.DURATION_S] = duration_s
        return rows, ts, vals, closes

    # vectorized backfill straight into the device buffers (the REST
    # backfill path is exercised by tests; seeding 1.6M bars through
    # per-dict parsing would dominate bench startup for no extra fidelity)
    state = engine.state
    for b in range(window):
        rows, ts, vals, px = make_updates(t0 + b * 900, px, 900)
        state = state._replace(
            buf5=apply_updates(state.buf5, rows, ts, vals),
            buf15=apply_updates(state.buf15, rows, ts, vals),
        )
    # exactly `window` appends happen to wrap the cursor back to 0, but
    # canonicalize explicitly so the seed stays right-aligned if the
    # fill count ever changes
    from binquant_tpu.engine.step import canonicalize_state

    engine.state = canonicalize_state(state)
    jax.block_until_ready(engine.state.buf15.values)
    return engine, make_updates, t0 + window * 900, px


# Measurement-epoch stamp (VERDICT r4 weak #7): how numbers were synced and
# since when they are comparable. Epoch 2 = real packed-wire D2H fetch
# (np.asarray) — round 4 exposed `block_until_ready` as a near-no-op
# through the tunneled chip, so epoch-1 numbers (rounds ≤3, e.g. r3's 953k
# evals/s) are inflated and NOT comparable.
MEASUREMENT_EPOCH = {
    "epoch": 2,
    "sync_method": "packed-wire D2H fetch (np.asarray); per-phase final sync",
    "comparable_since": "BENCH_r04",
    "note": (
        "epoch-1 (<= round 3) numbers used block_until_ready, which does "
        "not block through the tunneled device — do not compare across "
        "epochs"
    ),
}


def _git_sha() -> str:
    """Short git SHA of the measured tree (ISSUE 15 satellite: every bench
    record orders deterministically in BENCH_TRAJECTORY.json)."""
    import subprocess

    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5,
            ).stdout.strip()
            or "unknown"
        )
    except Exception:
        return "unknown"


def _stamped(record: dict) -> dict:
    """Uniform record stamp: wall-clock measurement time + git SHA (for
    deterministic ordering by tools/bench_trajectory.py) and the
    MEASUREMENT_EPOCH methodology note on the detail section. Mutates and
    returns ``record`` so print- and file-writers share one stamped dict."""
    record.setdefault("measured_at_epoch_s", int(time.time()))
    record.setdefault("git_sha", _git_sha())
    detail = record.get("detail")
    if isinstance(detail, dict):
        detail.setdefault("measurement_epoch", MEASUREMENT_EPOCH)
    return record


def device_cost_breakdown(
    num_symbols: int = 2048,
    window: int = 400,
    iters: int = 30,
    per_strategy: bool = False,
) -> dict:
    """Device-side cost of the tick step (VERDICT r4 item 2).

    Measures the jit'd step in isolation — N back-to-back dispatches, one
    final D2H sync, divided by N — so the number is device execution time
    free of per-tick RTT. Reports:

    * ``step_ms`` — the production wire path (``tick_step_wire``: only the
      enabled live strategies compiled, dormant kernels DCE'd out);
    * ``step_all_ms`` — the full-capability variant (all 14 strategy
      kernels, the overflow-fallback/full-outputs path);
    * ``stages`` — cumulative partial pipelines (buffer update → feature
      packs → context/regimes → full wire step); per-stage cost is the
      increment between consecutive rows. Increments are approximate:
      XLA fuses across stage boundaries, so a stage's standalone cost can
      shift when later consumers change its fusion partners.
    * ``flops`` / ``bytes_accessed`` — XLA ``cost_analysis`` of the wire
      executable (per tick);
    * ``duty_cycle_1s`` — step_ms / 1000 ms cadence: the fraction of the
      chip the engine occupies at the live cadence (single-chip headroom);
    * ``incremental`` — the SAME wire step with ``incremental=True`` (the
      live fast path: carried indicator + strategy-stage state advanced by
      the newest bar instead of full-window recompute): step time,
      cost_analysis bytes/flops, and the reduction ratios vs the full
      step. This is the bytes-per-tick phase ISSUE 2 prescribes — the
      tick was measured bytes-bound (VERDICT r5: ~11.8 GB/tick for
      1.9 GFLOP), so ``bytes_reduction_x`` is the number that predicts the
      headroom win.
    * ``donated`` — the incremental wire step through the DONATED
      executable (the live default since ISSUE 4): ring buffers update in
      place, erasing the functional scatter's allocate+copy. Step time is
      measured by threading the state through back-to-back donated calls
      (exactly the live pipeline's usage).
    * ``per_strategy_bytes`` (opt-in: ``per_strategy=True``, the
      ``--device`` mode) — bytes attribution BY EXCLUSION: recompile the
      wire with each live strategy removed from ``wire_enabled`` and
      report the delta, for the classic and incremental variants. Proves
      where the bytes went (ISSUE 4: the ABP windowed-sort residue must
      vanish from the incremental column).
    """
    import jax

    from binquant_tpu.engine.buffer import (
        apply_updates,
        materialize,
        materialize_tail,
    )
    from binquant_tpu.engine.step import (
        INCR_TAIL_WINDOW,
        HostInputs,
        init_indicator_carry,
        pad_updates,
        tick_step,
        tick_step_wire,
        tick_step_wire_donated,
    )
    from binquant_tpu.regime.context import compute_market_context
    from binquant_tpu.strategies.features import (
        compute_feature_pack,
        compute_feature_pack_incremental,
    )

    engine, make_updates, now, px = _seed_engine(num_symbols, window, 0)
    cfg = engine.context_config
    key = engine._wire_enabled_key()
    S = num_symbols

    inputs = HostInputs(
        tracked=np.ones(S, bool),
        btc_row=np.int32(0),
        timestamp_s=np.int32(now - 900),
        timestamp5_s=np.int32(now - 300),
        oi_growth=np.full(S, np.nan, np.float32),
        adp_latest=np.float32(np.nan),
        adp_prev=np.float32(np.nan),
        adp_diff=np.float32(np.nan),
        adp_diff_prev=np.float32(np.nan),
        breadth_momentum_points=np.float32(np.nan),
        quiet_hours=np.bool_(False),
        grid_policy_allows=np.bool_(False),
        is_futures=np.bool_(True),
        dominance_is_losers=np.bool_(False),
        market_domination_reversal=np.bool_(False),
    )
    rows, t15, v15, _ = make_updates(now - 900, px, 900)
    rows5, t5, v5, _ = make_updates(now - 300, px, 300)
    # pre-stage the update batches on device: the per-tick H2D of these
    # arrays is a DISPATCH cost (measured by the engine-level phases);
    # leaving it in this loop would bill tunnel bandwidth to the device
    # stages (~8 ms/call at S=8192 through the tunnel)
    u15 = jax.device_put(pad_updates(rows, t15, v15, S))
    u5 = jax.device_put(pad_updates(rows5, t5, v5, S))
    inputs = jax.device_put(inputs)
    state = engine.state
    # sync the indicator carry to the seeded windows (the seed path writes
    # buffers directly, bypassing the engine's full-tick resync); BTC is
    # registry row 0 in the seeded universe
    state = state._replace(
        indicator_carry=jax.jit(
            lambda b5, b15: init_indicator_carry(b5, b15, 0)
        )(state.buf5, state.buf15)
    )

    from binquant_tpu.engine.buffer import fresh_mask

    import jax.numpy as jnp

    def _consume(*arrs):
        # a full-reduction sink so XLA cannot DCE the stage under test
        return sum(jnp.sum(jnp.asarray(a, jnp.float32)) for a in arrs)

    @jax.jit
    def f_update(state, u5, u15):
        b5 = apply_updates(state.buf5, *u5)
        b15 = apply_updates(state.buf15, *u15)
        return _consume(b5.values, b15.values, b5.times, b15.times)

    @jax.jit
    def f_packs(state, u5, u15):
        # window kernels read canonical views — the per-tick materialize
        # is part of the classic stage cost since the cursor ring
        b5 = materialize(apply_updates(state.buf5, *u5))
        b15 = materialize(apply_updates(state.buf15, *u15))
        p5 = compute_feature_pack(b5)
        p15 = compute_feature_pack(b15)
        return _consume(*[x for x in p5 if x.ndim], *[x for x in p15 if x.ndim])

    @jax.jit
    def f_packs_incr(state, u5, u15):
        # the incremental path's hoisted tail view (engine/step.py)
        b5 = materialize_tail(
            apply_updates(state.buf5, *u5), INCR_TAIL_WINDOW
        )
        b15 = materialize_tail(
            apply_updates(state.buf15, *u15), INCR_TAIL_WINDOW
        )
        p5, _ = compute_feature_pack_incremental(
            b5, state.indicator_carry.pack5
        )
        p15, _ = compute_feature_pack_incremental(
            b15, state.indicator_carry.pack15
        )
        return _consume(*[x for x in p5 if x.ndim], *[x for x in p15 if x.ndim])

    @jax.jit
    def f_context(state, u5, u15, inputs):
        b5 = materialize(apply_updates(state.buf5, *u5))
        b15 = materialize(apply_updates(state.buf15, *u15))
        p5 = compute_feature_pack(b5)
        p15 = compute_feature_pack(b15)
        ctx, carry = compute_market_context(
            b15,
            fresh_mask(b15, inputs.timestamp_s),
            inputs.tracked,
            inputs.btc_row,
            inputs.timestamp_s,
            state.regime_carry,
            cfg,
        )
        leaves = [x for x in jax.tree_util.tree_leaves((ctx, carry)) if x.ndim]
        return _consume(
            *[x for x in p5 if x.ndim], *[x for x in p15 if x.ndim], *leaves
        )

    def f_wire(state, u5, u15, inputs):
        # the CLASSIC comparator: pre-ISSUE-2 semantics, i.e. no carry
        # maintenance (maintain_carry=True would bill the fast path's
        # resync machinery to the baseline and inflate every ratio)
        _, wire = tick_step_wire(
            state, u5, u15, inputs, cfg, wire_enabled=key,
            maintain_carry=False,
        )
        return wire

    def f_wire_resync(state, u5, u15, inputs):
        # the fallback/audit tick the incremental mode actually dispatches:
        # full recompute + carry re-init from the windows
        _, wire = tick_step_wire(state, u5, u15, inputs, cfg, wire_enabled=key)
        return wire

    def f_wire_incr(state, u5, u15, inputs):
        _, wire = tick_step_wire(
            state, u5, u15, inputs, cfg, wire_enabled=key, incremental=True
        )
        return wire

    def f_all(state, u5, u15, inputs):
        _, out = tick_step(
            state, u5, u15, inputs, cfg, wire_enabled=key, maintain_carry=False
        )
        return out.wire

    def timed(fn, *args) -> float:
        r = fn(*args)  # compile + warm
        np.asarray(r)
        r = fn(*args)
        np.asarray(r)
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(*args)
        np.asarray(r)
        return (time.perf_counter() - t0) / iters * 1000.0

    # per-dispatch floor of the link (async dispatch of a trivial jit in
    # the same loop shape): stage increments smaller than this are noise —
    # through the tunneled chip it is several ms, on a local chip ~0
    tiny = jax.jit(lambda x: x + 1.0)
    floor_ms = timed(tiny, jnp.zeros((), jnp.float32))

    # stages_cumulative_ms stays a strictly CUMULATIVE sequence of the
    # classic pipeline (per-stage cost = increment between consecutive
    # rows); the incremental pack stage is a sibling measurement and
    # reports under detail.incremental instead
    def timed_donated(iters_d: int = iters) -> float:
        """Back-to-back donated steps threading the state (the live
        pipeline's usage — each call consumes its input state)."""
        st = jax.tree_util.tree_map(jnp.copy, state)
        # compile + warm
        st, r = tick_step_wire_donated(
            st, u5, u15, inputs, cfg, wire_enabled=key, incremental=True
        )
        np.asarray(r)
        t0 = time.perf_counter()
        for _ in range(iters_d):
            st, r = tick_step_wire_donated(
                st, u5, u15, inputs, cfg, wire_enabled=key, incremental=True
            )
        np.asarray(r)
        return (time.perf_counter() - t0) / iters_d * 1000.0

    stages = {
        "buffer_update": timed(f_update, state, u5, u15),
        "plus_feature_packs": timed(f_packs, state, u5, u15),
        "plus_context_regimes": timed(f_context, state, u5, u15, inputs),
        "full_wire_step": timed(f_wire, state, u5, u15, inputs),
    }
    step_ms = stages["full_wire_step"]
    packs_incr_ms = timed(f_packs_incr, state, u5, u15)
    step_incr_ms = timed(f_wire_incr, state, u5, u15, inputs)
    step_resync_ms = timed(f_wire_resync, state, u5, u15, inputs)
    step_donated_ms = timed_donated()
    step_all_ms = timed(f_all, state, u5, u15, inputs)

    def _cost_of(fn=tick_step_wire, wire_key=None, **lower_kwargs) -> dict:
        try:
            compiled = fn.lower(
                state, u5, u15, inputs, cfg,
                wire_enabled=key if wire_key is None else wire_key,
                **lower_kwargs,
            ).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return {
                "flops": float(ca.get("flops", float("nan"))),
                "bytes_accessed": float(ca.get("bytes accessed", float("nan"))),
            }
        except Exception:  # cost_analysis availability varies by backend
            return {"flops": None, "bytes_accessed": None}

    # classic baseline: pre-ISSUE-2 semantics (no carry maintenance)
    cost = _cost_of(maintain_carry=False)
    cost_incr = _cost_of(incremental=True)
    cost_donated = _cost_of(fn=tick_step_wire_donated, incremental=True)
    # numeric-health digest (ISSUE 7): cost of the wire step with the
    # device-computed digest block on — its acceptance budget is <5% extra
    # bytes over the digest-off incremental step. The classic arm records
    # the OTHER path too (ISSUE 9 satellite): since the digest's classic
    # feature-stage scan was cut to the wire-materialized pack fields, the
    # classic overhead is a tracked number instead of a NOTE.
    cost_digest = _cost_of(incremental=True, numeric_digest=True)
    cost_digest_classic = _cost_of(maintain_carry=False, numeric_digest=True)
    # ingest-health digest (ISSUE 15): same acceptance framing — the
    # ingest block's wire-step byte overhead must stay <5% over the
    # digest-off step on BOTH paths, and the production stack carries
    # numeric + ingest together, so that combination is recorded too
    cost_ingest = _cost_of(incremental=True, ingest_digest=True)
    cost_ingest_classic = _cost_of(maintain_carry=False, ingest_digest=True)
    cost_obs_stack = _cost_of(
        incremental=True, numeric_digest=True, ingest_digest=True
    )

    def _ratio(full, incr):
        if not full or not incr or incr != incr or full != full:
            return None
        return round(full / incr, 2) if incr > 0 else None

    def _overhead_pct(on, off):
        if on is None or off is None or on != on or off != off or not off:
            return None
        return round((on / off - 1.0) * 100.0, 3)

    # bytes attribution by exclusion: recompile with one strategy removed
    # and credit the delta to it (XLA fusion makes deltas approximate; a
    # negative rounding residue reads as ~0)
    per_strategy_bytes = None
    if per_strategy:
        per_strategy_bytes = {}
        for name in key:
            reduced = tuple(s for s in key if s != name)
            drop_classic = _cost_of(wire_key=reduced, maintain_carry=False)
            drop_incr = _cost_of(wire_key=reduced, incremental=True)

            def _delta(full_c, red_c):
                f, r = full_c.get("bytes_accessed"), red_c.get("bytes_accessed")
                if f is None or r is None or f != f or r != r:
                    return None
                return round(max(f - r, 0.0) / 1e9, 4)

            per_strategy_bytes[name] = {
                "classic_gb": _delta(cost, drop_classic),
                "incremental_gb": _delta(cost_incr, drop_incr),
            }

    return {
        "symbols": num_symbols,
        "window": window,
        "step_ms": round(step_ms, 3),
        "step_incremental_ms": round(step_incr_ms, 3),
        "step_all_ms": round(step_all_ms, 3),
        "dispatch_floor_ms": round(floor_ms, 3),
        "stages_cumulative_ms": {k: round(v, 3) for k, v in stages.items()},
        "duty_cycle_1s": round(step_ms / 1000.0, 4),
        "live_evals_per_sec": round(num_symbols * len(key) / (step_ms / 1000.0)),
        "full_evals_per_sec": round(num_symbols * 14 / (step_all_ms / 1000.0)),
        **cost,
        # the bytes-per-tick phase: incremental (carried indicator state)
        # vs full-recompute wire step, same inputs, same enabled set
        "incremental": {
            "step_ms": round(step_incr_ms, 3),
            # buffer update + packs via carry (sibling of the cumulative
            # table's plus_feature_packs row)
            "stage_packs_ms": round(packs_incr_ms, 3),
            # the fallback/audit tick's cost (full recompute + carry
            # re-init) — what an incremental deployment pays on resync
            "full_step_with_carry_resync_ms": round(step_resync_ms, 3),
            "duty_cycle_1s": round(step_incr_ms / 1000.0, 4),
            "live_evals_per_sec": round(
                num_symbols * len(key) / (step_incr_ms / 1000.0)
            ),
            **cost_incr,
            "bytes_reduction_x": _ratio(
                cost.get("bytes_accessed"), cost_incr.get("bytes_accessed")
            ),
            "flops_reduction_x": _ratio(
                cost.get("flops"), cost_incr.get("flops")
            ),
            "step_time_cut_x": _ratio(step_ms, step_incr_ms),
        },
        # the live default since ISSUE 4: incremental + donated buffers
        "donated": {
            "step_ms": round(step_donated_ms, 3),
            **cost_donated,
            "bytes_reduction_x_vs_classic": _ratio(
                cost.get("bytes_accessed"), cost_donated.get("bytes_accessed")
            ),
            "step_time_cut_x_vs_classic": _ratio(step_ms, step_donated_ms),
        },
        # ISSUE 7 acceptance: the digest's wire-step byte overhead (<5%).
        # NaN-checked explicitly (a backend without cost_analysis must
        # yield null, not a bare NaN token in the checked-in JSON record)
        # and NOT routed through _ratio, whose 2-decimal rounding would
        # quantize the sub-1% number the acceptance gate reads.
        "numeric_digest": {
            **cost_digest,
            "bytes_overhead_pct": _overhead_pct(
                cost_digest.get("bytes_accessed"),
                cost_incr.get("bytes_accessed"),
            ),
            # classic (non-incremental) wire with the cheapened
            # wire-fields-only feature scan, vs the digest-off classic step
            "classic": {
                **cost_digest_classic,
                "bytes_overhead_pct": _overhead_pct(
                    cost_digest_classic.get("bytes_accessed"),
                    cost.get("bytes_accessed"),
                ),
            },
        },
        # ISSUE 15 acceptance: the ingest digest's wire-step byte overhead
        # (<5%), same NaN handling/rounding rules as the numeric arm above
        "ingest_digest": {
            **cost_ingest,
            "bytes_overhead_pct": _overhead_pct(
                cost_ingest.get("bytes_accessed"),
                cost_incr.get("bytes_accessed"),
            ),
            "classic": {
                **cost_ingest_classic,
                "bytes_overhead_pct": _overhead_pct(
                    cost_ingest_classic.get("bytes_accessed"),
                    cost.get("bytes_accessed"),
                ),
            },
            # the deployed observability stack (numeric + ingest digests
            # both on) vs the digest-free incremental wire
            "with_numeric_stack": {
                **cost_obs_stack,
                "bytes_overhead_pct": _overhead_pct(
                    cost_obs_stack.get("bytes_accessed"),
                    cost_incr.get("bytes_accessed"),
                ),
            },
        },
        "per_strategy_bytes": per_strategy_bytes,
    }


def run_sweep(window: int = 400, sizes: tuple[int, ...] = (1024, 2048, 4096, 8192)) -> dict:
    """Scaling map (VERDICT r4 item 3): device step cost vs symbol count
    at the production window, plus the stated max-S at the 1 s cadence."""
    points = [device_cost_breakdown(s, window, iters=20) for s in sizes]
    # max-S at 1 s cadence: largest measured S whose device step + measured
    # host dispatch cost (~7 ms) fits the cadence. When every measured
    # point fits, the number is a LINEAR EXTRAPOLATION from the last
    # octave's slope all the way to the cadence budget — i.e. well beyond
    # the data (~12x at the current table); treat it as an estimate, not a
    # measurement (the README labels it as extrapolated).
    def extrapolate(step_key) -> int | None:
        fits = [p for p in points if step_key(p) + 7.0 < 1000.0]
        if not fits:
            return None
        last = fits[-1]
        if last is not points[-1]:
            return fits[-1]["symbols"]
        prev = points[-2] if len(points) >= 2 else last
        slope = max(
            (step_key(last) - step_key(prev))
            / max(last["symbols"] - prev["symbols"], 1),
            1e-6,
        )
        return int(last["symbols"] + (1000.0 - 7.0 - step_key(last)) / slope)

    return {
        "window": window,
        "points": points,
        "max_symbols_at_1s_cadence": extrapolate(lambda p: p["step_ms"]),
        # the incremental fast path's ceiling (same extrapolation caveat)
        "max_symbols_at_1s_cadence_incremental": extrapolate(
            lambda p: p["step_incremental_ms"]
        ),
    }


def run_outcome_cost(
    num_symbols: int = 2048, window: int = 400, pairs: int | None = None
) -> dict:
    """Signal-outcome maturation cost (ISSUE 12 acceptance: the gather
    must add <5% of the wire step's bytes at 2048x400).

    Both numbers come from XLA cost_analysis of the lowered executables —
    the same arbiter the numeric-digest budget uses: the denominator is
    the INCREMENTAL wire step (the live engine's per-tick executable),
    the numerator the maturation gather at a ``pairs``-slot bucket (128 =
    the wire's own compaction width — a full fired tick's worth of
    (signal, horizon) pairs maturing at once, far above the steady-state
    handful)."""
    import jax
    import jax.numpy as jnp

    from binquant_tpu.engine.buffer import NUM_FIELDS
    from binquant_tpu.engine.step import (
        WIRE_MAX_FIRED,
        initial_engine_state,
        default_host_inputs,
        pad_updates,
        tick_step_wire,
    )
    from binquant_tpu.obs.ledger import lowered_cost
    from binquant_tpu.obs.outcomes import _outcome_gather_impl
    from binquant_tpu.regime.context import ContextConfig

    S, W = num_symbols, window
    if pairs is None:
        # the compaction width IS the worst-case maturation bucket the
        # docstring promises — follow it if the wire is ever retuned
        pairs = WIRE_MAX_FIRED
    cfg = ContextConfig()
    state = initial_engine_state(S, window=W)
    inputs = default_host_inputs(S)
    upd = pad_updates(
        np.zeros(0, np.int32), np.zeros(0, np.int32),
        np.zeros((0, NUM_FIELDS), np.float32), size=4,
    )
    wire_cost = lowered_cost(
        tick_step_wire, state, upd, upd, inputs, cfg, incremental=True
    )

    K = pairs
    abstract = jax.ShapeDtypeStruct
    gather_cost = lowered_cost(
        jax.jit(_outcome_gather_impl),
        abstract((S, W), jnp.int32),
        abstract((S, W, NUM_FIELDS), jnp.float32),
        abstract((K,), jnp.int32),
        abstract((K,), jnp.int32),
        abstract((K,), jnp.int32),
    )

    def _pct(num, den):
        if num is None or den is None or not den:
            return None
        return round(100.0 * num / den, 3)

    pct = _pct(
        gather_cost.get("bytes_accessed"), wire_cost.get("bytes_accessed")
    )
    return {
        "symbols": S,
        "window": W,
        "pairs": K,
        "wire_step_incremental": wire_cost,
        "outcome_gather": gather_cost,
        "gather_vs_wire_bytes_pct": pct,
        "budget_pct": 5.0,
        "ok": pct is not None and pct < 5.0,
        "measurement": (
            "XLA cost_analysis of the lowered executables (no execution): "
            "tick_step_wire incremental at the production shape vs the "
            "outcome maturation gather at a full compaction-width pair "
            "bucket. The gather runs at most once per finalize and only "
            "when pairs are due, so the per-tick average is far below "
            "this worst case."
        ),
        "measurement_epoch": MEASUREMENT_EPOCH,
    }


def run_fanout_throughput(
    n_subs: int = 1_000_000,
    fired: int = 8,
    iters: int = 20,
    oracle_sample: int = 20_000,
    replay_symbols: int = 16,
    replay_ticks: int = 60,
    replay_subs: int = 10_000,
) -> dict:
    """Subscription fan-out match-kernel throughput (ISSUE 14).

    Arm 1 (headline): bulk-load ``n_subs`` subscriptions (mixed symbol/
    strategy/regime criteria + per-user strength floors) into the packed
    bitset planes, push them to the device once, then measure the ONE
    jit'd dispatch that joins ``fired`` fired slots against the whole
    population — (subscriptions x fired-signals)/s, with the pure-Python
    oracle extrapolated from a sample as the what-it-replaces baseline
    (the ROADMAP's "a million subscriptions costs one extra kernel, not
    a Python loop").

    Arm 2 (integration overhead): an identical replayed burst through
    the serial drive with the plane ON (``replay_subs`` subscribers) vs
    BQT_FANOUT=0 — median tick wall both ways (the plane must not tax
    unfired ticks) plus the measured match cost per FIRED tick (sync
    check + pad + dispatch + packed-words D2H), compile excluded by
    pre-warming the fired-count buckets."""
    from binquant_tpu.engine.step import STRATEGY_ORDER
    from binquant_tpu.enums import MarketRegimeCode
    from binquant_tpu.fanout.kernel import DevicePlanes, popcount_words
    from binquant_tpu.fanout.registry import (
        INVALID_REGIME_ROW,
        Subscription,
        SubscriptionRegistry,
    )

    # -- arm 1: the 1M-subscription single-dispatch join --------------------
    sym_rows = {f"S{j:03d}USDT": j for j in range(64)}
    symbols = list(sym_rows)
    n_regimes = len(MarketRegimeCode)

    def make_sub(i: int) -> Subscription:
        return Subscription(
            f"u{i}",
            symbols=(
                frozenset({symbols[i % len(symbols)]})
                if i % 4 == 0
                else None
            ),
            strategies=frozenset({STRATEGY_ORDER[i % len(STRATEGY_ORDER)]}),
            regimes=(
                frozenset({i % n_regimes}) if i % 8 == 0 else None
            ),
            min_strength=(i % 100) / 100.0,
        )

    t0 = time.perf_counter()
    subs = [make_sub(i) for i in range(n_subs)]
    build_s = time.perf_counter() - t0
    reg = SubscriptionRegistry(symbol_capacity=64, capacity=n_subs)
    t0 = time.perf_counter()
    reg.bulk_load(subs, row_of=sym_rows.get)
    bulk_load_s = time.perf_counter() - t0
    dev = DevicePlanes(reg)
    t0 = time.perf_counter()
    assert dev.sync() == "full"
    sync_s = time.perf_counter() - t0

    rng = np.random.default_rng(14)
    rows = rng.integers(0, 64, size=fired).astype(np.int32)
    strats = rng.integers(0, len(STRATEGY_ORDER), size=fired).astype(
        np.int32
    )
    scores = np.float32(rng.normal(0, 0.6, size=fired))
    for _ in range(2):  # compile + steady-state warmup
        dev.match(rows, strats, scores, 0)
    dispatch_s: list[float] = []
    recipients = 0
    for it in range(iters):
        sc = np.float32(rng.normal(0, 0.6, size=fired))
        t0 = time.perf_counter()
        words = dev.match(rows, strats, sc, it % n_regimes)
        dispatch_s.append(time.perf_counter() - t0)  # np.asarray = D2H sync
        recipients = popcount_words(words)
    best_s = min(dispatch_s)

    # the Python oracle, extrapolated from a sample population (running
    # it at 1M would take minutes — which is the point)
    sample_reg = SubscriptionRegistry(
        symbol_capacity=64, capacity=oracle_sample
    )
    sample_reg.bulk_load(
        [make_sub(i) for i in range(oracle_sample)], row_of=sym_rows.get
    )
    entries = [
        (STRATEGY_ORDER[si], symbols[ri], float(sc))
        for si, ri, sc in zip(strats, rows, scores)
    ]
    t0 = time.perf_counter()
    sample_reg.match_oracle(entries, 0)
    oracle_sample_s = time.perf_counter() - t0
    oracle_s_est = oracle_sample_s * (n_subs / oracle_sample)

    # -- arm 2: per-tick overhead vs BQT_FANOUT=0 over one replay -----------
    import tempfile

    from binquant_tpu.fanout.kernel import bucket as _bucket
    from binquant_tpu.io.replay import (
        generate_replay_file,
        load_klines_by_tick,
        make_stub_engine,
    )

    stream = tempfile.mktemp(prefix="bqt_fanout_bench_", suffix=".jsonl")
    generate_replay_file(
        stream, n_symbols=replay_symbols, n_ticks=replay_ticks
    )
    by_tick = load_klines_by_tick(stream)
    seq = [
        (
            (b + 1) * 900 * 1000,
            sorted(by_tick[b], key=lambda k: k["open_time"]),
        )
        for b in sorted(by_tick)
    ]

    def drive(engine) -> list[float]:
        ticks: list[float] = []

        async def go():
            for now_ms, klines in seq:
                for k in klines:
                    engine.ingest(k)
                t0 = time.perf_counter()
                await engine.process_tick(now_ms=now_ms)
                ticks.append((time.perf_counter() - t0) * 1000)
            await engine.flush_pending()
            await engine.aclose_fanout()

        asyncio.run(go())
        return ticks[5:]  # drop engine-compile warmup ticks

    # delivery pinned off like every bench lane (inline sinks): the arm
    # quotes the PLANE's overhead, and an un-aclosed delivery plane's
    # workers would wedge asyncio.run teardown. A throwaway engine pays
    # the engine-executable compiles first so BOTH timed drives run on a
    # warm jit cache (the compile bill would otherwise land entirely on
    # whichever arm drives first and swamp the comparison).
    drive(
        make_stub_engine(
            capacity=replay_symbols, window=120, fanout=False,
            delivery=False,
        )
    )
    off = make_stub_engine(
        capacity=replay_symbols, window=120, fanout=False, delivery=False
    )
    ticks_off = drive(off)

    on = make_stub_engine(
        capacity=replay_symbols, window=120, fanout=True, delivery=False
    )
    on.fanout.bulk_load(
        [make_sub(i) for i in range(replay_subs)]
    )
    # pre-warm the fired-count pad buckets so arm-2 timings exclude the
    # match kernel's compile (it retraces per power-of-two bucket only)
    on.fanout.sync_device()
    for k in (1, _bucket(4) + 1, _bucket(8) + 1):
        on.fanout._device.match(
            np.zeros(k, np.int32),
            np.zeros(k, np.int32),
            np.zeros(k, np.float32),
            INVALID_REGIME_ROW,
        )
    match_acc = {"s": 0.0, "n": 0}
    orig_match = on.fanout.match

    def timed_match(fired_signals, ctx_scalars):
        t0 = time.perf_counter()
        words = orig_match(fired_signals, ctx_scalars)
        match_acc["s"] += time.perf_counter() - t0
        match_acc["n"] += 1
        return words

    on.fanout.match = timed_match
    ticks_on = drive(on)

    med_off = float(np.median(ticks_off))
    med_on = float(np.median(ticks_on))
    return {
        "subscriptions": n_subs,
        "fired_slots": fired,
        "plane_words": reg.words,
        "build_population_s": round(build_s, 3),
        "bulk_load_s": round(bulk_load_s, 3),
        "device_full_sync_s": round(sync_s, 3),
        "match_dispatch_ms_best": round(best_s * 1000, 3),
        "match_dispatch_ms_mean": round(
            float(np.mean(dispatch_s)) * 1000, 3
        ),
        "sub_signal_matches_per_s": round(n_subs * fired / best_s),
        "last_match_recipients": recipients,
        "python_oracle_s_at_1m_est": round(oracle_s_est, 3),
        "python_oracle_sampled": oracle_sample,
        "speedup_vs_python_oracle_x": round(oracle_s_est / best_s, 1),
        "replay_overhead": {
            "symbols": replay_symbols,
            "ticks": len(seq),
            "subscribers": replay_subs,
            "tick_median_ms_fanout_off": round(med_off, 3),
            "tick_median_ms_fanout_on": round(med_on, 3),
            "overhead_median_ms_per_tick": round(med_on - med_off, 3),
            "fired_ticks_matched": match_acc["n"],
            "match_ms_per_fired_tick": (
                round(match_acc["s"] / match_acc["n"] * 1000, 3)
                if match_acc["n"]
                else None
            ),
        },
        "note": (
            "CPU-model numbers — rerun on silicon when the tunnel "
            "returns."
        ),
        "measurement_epoch": MEASUREMENT_EPOCH,
    }


def run_fanout_churn_scale(
    sizes: tuple = (10_000, 100_000, 1_000_000),
    bursts: int = 24,
    ops_per_burst: int = 32,
) -> dict:
    """Sustained-churn scaling (ISSUE 20 tentpole): at each resident
    population size, apply ``bursts`` ticks of paired subscription churn
    (add + remove + modify per op, population held stable) and sync the
    device after each burst — the per-delta apply cost must stay FLAT
    from 10k to 1M residents (the delta plane patches one word per dirty
    cell; the pre-ISSUE-20 column scatter re-shipped O(symbol) columns
    per op, and before that the bulk path re-packed the whole plane).
    Asserts zero bulk rebuilds after the initial full push: any ``full``
    resync during the churn phase means the incremental plane leaked a
    capacity bump or a dirty-tracking hole."""
    from binquant_tpu.engine.step import STRATEGY_ORDER
    from binquant_tpu.enums import MarketRegimeCode
    from binquant_tpu.fanout.kernel import DevicePlanes
    from binquant_tpu.fanout.registry import Subscription, SubscriptionRegistry

    sym_rows = {f"S{j:03d}USDT": j for j in range(64)}
    symbols = list(sym_rows)
    n_regimes = len(MarketRegimeCode)

    def make_sub(uid: str, i: int) -> Subscription:
        return Subscription(
            uid,
            symbols=(
                frozenset({symbols[i % len(symbols)]})
                if i % 4 == 0
                else None
            ),
            strategies=frozenset({STRATEGY_ORDER[i % len(STRATEGY_ORDER)]}),
            regimes=(frozenset({i % n_regimes}) if i % 8 == 0 else None),
            min_strength=(i % 100) / 100.0,
        )

    rungs: list[dict] = []
    for n in sizes:
        reg = SubscriptionRegistry(symbol_capacity=64, capacity=n)
        reg.bulk_load(
            [make_sub(f"u{i}", i) for i in range(n)], row_of=sym_rows.get
        )
        dev = DevicePlanes(reg)
        assert dev.sync() == "full"
        # warm the delta-kernel pad buckets for this burst size so the
        # timed loop measures steady-state patches, not the first trace
        rng = np.random.default_rng(20)
        syncs = {"incremental": 0, "full": 0, None: 0}
        burst_ms: list[float] = []
        delta_words: list[int] = []
        victim = 0
        for b in range(bursts):
            t0 = time.perf_counter()
            for op in range(ops_per_burst):
                i = victim % n
                victim += 1
                uid = f"u{i}"
                # paired churn keeps the population (and capacity)
                # stable: remove an existing resident, re-add it with a
                # rotated criteria set, modify another in place
                reg.remove(uid)
                reg.add(
                    make_sub(uid, i + 7 * (b + 1)), row_of=sym_rows.get
                )
                j = int(rng.integers(0, n))
                reg.update(
                    make_sub(f"u{j}", j + 13 * (b + 1)),
                    row_of=sym_rows.get,
                )
            kind = dev.sync()
            burst_ms.append((time.perf_counter() - t0) * 1000.0)
            syncs[kind] = syncs.get(kind, 0) + 1
            delta_words.append(dev.last_delta_words)
        arr = np.asarray(burst_ms[2:] or burst_ms)  # drop trace warmup
        per_delta = arr / (3 * ops_per_burst)  # 3 registry ops per op
        rungs.append(
            {
                "residents": n,
                "bursts": bursts,
                "ops_per_burst": 3 * ops_per_burst,
                "incremental_syncs": syncs.get("incremental", 0),
                "full_syncs_during_churn": syncs.get("full", 0),
                "delta_words_mean": round(float(np.mean(delta_words)), 1),
                "burst_ms_p50": round(float(np.percentile(arr, 50)), 3),
                "burst_ms_p99": round(float(np.percentile(arr, 99)), 3),
                "ms_per_delta_p50": round(
                    float(np.percentile(per_delta, 50)), 5
                ),
                "ms_per_delta_p99": round(
                    float(np.percentile(per_delta, 99)), 5
                ),
            }
        )
    flat = (
        round(
            rungs[-1]["ms_per_delta_p50"] / rungs[0]["ms_per_delta_p50"], 2
        )
        if rungs and rungs[0]["ms_per_delta_p50"]
        else None
    )
    return {
        "rungs": rungs,
        # O(1)-per-delta acceptance: the biggest rung's per-delta p50
        # over the smallest's — ~1.0 means resident count doesn't tax
        # churn at all (a bulk path would scale linearly, 100x here)
        "per_delta_flatness_1m_vs_10k_x": flat,
        "zero_bulk_rebuilds": all(
            r["full_syncs_during_churn"] == 0 for r in rungs
        ),
    }


def run_fanout_snapshot_warm(n_subs: int = 1_000_000) -> dict:
    """Snapshot-warm cold start (ISSUE 20 tentpole b): measure the full
    cold boot at ``n_subs`` (build population + bulk compile + device
    push) against the sidecar restore (archive load + column adopt +
    device push) — the restart path must come in ≥10x faster, killing
    the ~20 s fan-out outage the ROADMAP tracks."""
    import tempfile
    from pathlib import Path

    from binquant_tpu.engine.step import STRATEGY_ORDER
    from binquant_tpu.enums import MarketRegimeCode
    from binquant_tpu.fanout.kernel import DevicePlanes
    from binquant_tpu.fanout.registry import Subscription, SubscriptionRegistry
    from binquant_tpu.fanout.snapshot import load_snapshot, save_snapshot

    sym_rows = {f"S{j:03d}USDT": j for j in range(64)}
    symbols = list(sym_rows)
    n_regimes = len(MarketRegimeCode)

    def make_sub(i: int) -> Subscription:
        return Subscription(
            f"u{i}",
            symbols=(
                frozenset({symbols[i % len(symbols)]})
                if i % 4 == 0
                else None
            ),
            strategies=frozenset({STRATEGY_ORDER[i % len(STRATEGY_ORDER)]}),
            regimes=(frozenset({i % n_regimes}) if i % 8 == 0 else None),
            min_strength=(i % 100) / 100.0,
        )

    # -- the cold boot being killed: build + compile + push -----------------
    t0 = time.perf_counter()
    subs = [make_sub(i) for i in range(n_subs)]
    build_s = time.perf_counter() - t0
    cold = SubscriptionRegistry(symbol_capacity=64, capacity=n_subs)
    t0 = time.perf_counter()
    cold.bulk_load(subs, row_of=sym_rows.get)
    bulk_s = time.perf_counter() - t0
    dev = DevicePlanes(cold)
    t0 = time.perf_counter()
    assert dev.sync() == "full"
    push_s = time.perf_counter() - t0
    cold_boot_s = build_s + bulk_s + push_s

    # -- archive it (the save runs at checkpoint cadence, off the boot) -----
    path = Path(tempfile.mkdtemp(prefix="bqt_snapwarm_")) / "fanout.snap.npz"
    columns = cold.export_columns()
    columns["min_seq_slots"] = np.zeros(0, np.int64)
    columns["min_seq_vals"] = np.zeros(0, np.int64)
    planes = {
        "sym_plane": cold.sym_plane,
        "strat_plane": cold.strat_plane,
        "regime_plane": cold.regime_plane,
        "any_masks": cold.any_masks,
        "floors": cold.floors,
    }
    meta = {
        "capacity": cold.capacity,
        "symbol_capacity": 64,
        "strategy_order": list(STRATEGY_ORDER),
        "regime_rows": n_regimes + 1,
        "n_users": len(cold),
        "next_slot": cold._next_slot,
        "seq": 0,
        "fingerprint": "bench",
    }
    t0 = time.perf_counter()
    save_snapshot(path, planes, columns, meta, n_shards=1)
    save_s = time.perf_counter() - t0

    # -- the warm boot: load + adopt + push ---------------------------------
    t0 = time.perf_counter()
    warm = SubscriptionRegistry(symbol_capacity=64, capacity=1024)
    lplanes, lcolumns, lmeta = load_snapshot(path)
    users = warm.restore_columns(
        lplanes,
        lcolumns,
        capacity=int(lmeta["capacity"]),
        next_slot=int(lmeta["next_slot"]),
        rows_version=0,
    )
    wdev = DevicePlanes(warm)
    assert wdev.sync() == "full"
    warm_boot_s = time.perf_counter() - t0
    assert users == n_subs, (users, n_subs)

    # restored planes must be bit-identical to the cold build's
    planes_equal = all(
        np.array_equal(getattr(warm, k), getattr(cold, k))
        for k in (
            "sym_plane", "strat_plane", "regime_plane", "any_masks",
            "floors",
        )
    )
    archive_bytes = path.stat().st_size
    return {
        "subscriptions": n_subs,
        "cold_boot_s": round(cold_boot_s, 3),
        "cold_build_population_s": round(build_s, 3),
        "cold_bulk_load_s": round(bulk_s, 3),
        "cold_device_push_s": round(push_s, 3),
        "snapshot_save_s": round(save_s, 3),
        "snapshot_bytes": archive_bytes,
        "warm_boot_s": round(warm_boot_s, 3),
        "speedup_x": round(cold_boot_s / warm_boot_s, 1),
        "planes_bit_equal": bool(planes_equal),
    }


def run_fanout_connection_sweep(
    counts: tuple = (10_000, 100_000, 1_000_000),
    frames: int | tuple = (64, 32, 8),
    match_density: float = 0.2,
    slow_fraction: float = 0.01,
    conn_queue_max: int = 8,
) -> dict:
    """Connection-scale sweep over the broadcast tier (ISSUE 16,
    ROADMAP 2c): how the HUB itself scales from 10k to 100k concurrent
    consumers, independent of the match kernel (arm 1 covers that).

    Simulated consumers: real ``_Connection`` objects registered on a
    real ``FanoutHub`` driven through the production ``broadcast()``
    path (packed-word bit test + bounded offer + queue-depth sampling
    per connection), but drained inline instead of through sockets — at
    100k connections the sweep measures the fan-out loop and the
    backpressure contract, not the kernel's TCP stack. A ``slow_fraction``
    of consumers never drains: their bounded queues fill and overflow
    frames shed through the counted slow-consumer path, so each rung
    reports a real shed rate. Match→write latency is the ISSUE-16
    definition — ``t_pub`` stamped at frame mint through drain-side
    ``note_delivered`` — quoted at p50/p99 per rung. ``frames`` may be a
    per-rung tuple: the 1M rung (ISSUE 20's connection-scale ceiling)
    drives fewer frames so the sweep stays minutes-scale while still
    measuring the per-frame fan-out loop at that population."""
    from binquant_tpu.fanout.hub import FanoutHub, _Connection

    rng = np.random.default_rng(16)
    sweep: list[dict] = []
    for rung_idx, n_conns in enumerate(counts):
        n_frames = (
            int(frames[min(rung_idx, len(frames) - 1)])
            if isinstance(frames, (tuple, list))
            else int(frames)
        )
        hub = FanoutHub(slot_of=lambda u: None, conn_queue_max=conn_queue_max)
        conns = [
            _Connection(f"u{i}", i, "ws", conn_queue_max)
            for i in range(n_conns)
        ]
        hub._conns.update(conns)
        n_slow = max(int(n_conns * slow_fraction), 1)
        fast = conns[n_slow:]  # the first n_slow never drain

        n_words = (n_conns + 31) >> 5
        addressed = 0
        bcast_s: list[float] = []
        lags_ms: list[float] = []
        for seq in range(n_frames):
            mask = rng.random(n_conns) < match_density
            addressed += int(mask.sum())
            packed = np.packbits(mask, bitorder="little")
            packed = np.pad(packed, (0, (-len(packed)) % 4))
            words = packed.view(np.uint32)[:n_words]
            frame = {"seq": seq, "strategy": "bench", "symbol": "SWEEP"}
            t_pub = time.perf_counter()
            hub.broadcast(frame, words, t_pub)
            bcast_s.append(time.perf_counter() - t_pub)
            # responsive consumers drain between ticks; the slow cohort's
            # queues keep filling until the shed path takes over
            for conn in fast:
                while True:
                    try:
                        s, _, tp = conn.queue.get_nowait()
                    except asyncio.QueueFull:  # pragma: no cover
                        break
                    except asyncio.QueueEmpty:
                        break
                    conn.note_delivered(tp, s)
                    if tp is not None:
                        lags_ms.append(
                            (time.perf_counter() - tp) * 1000.0
                        )
        delivered = sum(c.delivered for c in conns)
        lags = np.asarray(lags_ms) if lags_ms else np.asarray([0.0])
        sweep.append(
            {
                "connections": n_conns,
                "frames": n_frames,
                "slow_consumers": n_slow,
                "addressed": addressed,
                "delivered": delivered,
                "shed": hub.shed,
                "shed_rate_pct": round(
                    100.0 * hub.shed / addressed, 3
                )
                if addressed
                else 0.0,
                "cursor_lag_records": hub.cursor_lag(),
                "broadcast_ms_per_frame": round(
                    float(np.mean(bcast_s)) * 1000, 3
                ),
                "frames_per_s": round(n_frames / sum(bcast_s)),
                "match_write_p50_ms": round(
                    float(np.percentile(lags, 50)), 3
                ),
                "match_write_p99_ms": round(
                    float(np.percentile(lags, 99)), 3
                ),
            }
        )
    return {
        "frames": list(frames) if isinstance(frames, (tuple, list)) else frames,
        "match_density": match_density,
        "slow_fraction": slow_fraction,
        "conn_queue_max": conn_queue_max,
        "sweep": sweep,
    }


def _shard_worker(
    n_devices: int, symbols: int, window: int, ticks: int, warmup: int
) -> None:
    """Child body for --shard-throughput: time the sharded wire step.

    Runs in a subprocess whose XLA_FLAGS pinned ``n_devices`` virtual CPU
    devices before jax import (``__graft_entry__._subprocess_env``). The
    state is assembled per-shard the way the production engine does it
    (``shard_engine_state`` → ``jax.make_array_from_single_device_arrays``),
    updates cover every row so the ingest H2D cost is the full-fat one,
    and the wire is fetched to host each tick (measurement epoch 2 sync).
    Prints ONE JSON line for the parent to collect."""
    import jax
    import jax.numpy as jnp

    from binquant_tpu.engine.buffer import NUM_FIELDS, Field
    from binquant_tpu.engine.step import (
        FIFTEEN_MIN_S,
        FIVE_MIN_S,
        default_host_inputs,
        initial_engine_state,
        tick_step_wire,
    )
    from binquant_tpu.parallel import (
        make_mesh,
        shard_engine_state,
        shard_host_inputs,
    )
    from binquant_tpu.parallel.mesh import assemble_sharded
    from binquant_tpu.regime.context import ContextConfig

    devices = jax.devices()[:n_devices]
    assert len(devices) == n_devices, (
        f"need {n_devices} devices, found {len(devices)}"
    )
    mesh = make_mesh(devices)
    cfg = ContextConfig()
    rng = np.random.default_rng(19)
    t0 = 1_753_000_200

    def full_ring(interval_s: int):
        # host-built canonical ring (cursor 0, right-aligned full): the
        # seeding that matters for throughput is the FULL window of
        # indicator input, not how the bars got there
        times = (
            t0
            + (np.arange(window, dtype=np.int64) - window) * interval_s
        ).astype(np.int32)
        times = np.broadcast_to(times, (symbols, window)).copy()
        px = 20.0 + rng.random((symbols, 1)).astype(np.float32) * 100
        walk = 1 + rng.normal(0, 0.004, (symbols, window)).astype(np.float32)
        closes = (px * np.cumprod(walk, axis=1)).astype(np.float32)
        vals = np.zeros((symbols, window, NUM_FIELDS), dtype=np.float32)
        vals[:, :, Field.OPEN] = closes
        vals[:, :, Field.CLOSE] = closes
        vals[:, :, Field.HIGH] = closes * 1.002
        vals[:, :, Field.LOW] = closes * 0.998
        vals[:, :, Field.VOLUME] = np.abs(
            rng.normal(1000, 150, (symbols, window))
        ).astype(np.float32)
        vals[:, :, Field.QUOTE_VOLUME] = vals[:, :, Field.VOLUME] * closes
        vals[:, :, Field.NUM_TRADES] = 150
        vals[:, :, Field.DURATION_S] = interval_s
        return times, vals

    state = initial_engine_state(symbols, window=window)
    t5, v5 = full_ring(FIVE_MIN_S)
    t15, v15 = full_ring(FIFTEEN_MIN_S)
    state = state._replace(
        buf5=state.buf5._replace(
            times=jnp.asarray(t5),
            values=jnp.asarray(v5),
            filled=jnp.full((symbols,), window, jnp.int32),
        ),
        buf15=state.buf15._replace(
            times=jnp.asarray(t15),
            values=jnp.asarray(v15),
            filled=jnp.full((symbols,), window, jnp.int32),
        ),
    )
    state = shard_engine_state(state, mesh)

    ts_now = t0
    inputs = default_host_inputs(symbols)._replace(
        tracked=np.ones(symbols, dtype=bool),
        btc_row=np.int32(0),
        timestamp_s=np.int32(ts_now),
        timestamp5_s=np.int32(ts_now),
    )
    inputs = shard_host_inputs(inputs, mesh)

    rows_np = np.arange(symbols, dtype=np.int32)
    last_close = v15[:, -1, Field.CLOSE].copy()

    def make_upd(ts_s: int):
        closes = last_close * (
            1 + rng.normal(0, 0.004, symbols).astype(np.float32)
        )
        vals = np.zeros((symbols, NUM_FIELDS), dtype=np.float32)
        vals[:, Field.OPEN] = last_close
        vals[:, Field.CLOSE] = closes
        vals[:, Field.HIGH] = np.maximum(last_close, closes) * 1.002
        vals[:, Field.LOW] = np.minimum(last_close, closes) * 0.998
        vals[:, Field.VOLUME] = np.abs(
            rng.normal(1000, 150, symbols)
        ).astype(np.float32)
        vals[:, Field.QUOTE_VOLUME] = vals[:, Field.VOLUME] * closes
        vals[:, Field.NUM_TRADES] = 150
        vals[:, Field.DURATION_S] = FIFTEEN_MIN_S
        last_close[:] = closes
        return np.full(symbols, ts_s, dtype=np.int32), vals

    place_s: list[float] = []
    step_s: list[float] = []

    for i in range(warmup + ticks):
        ts_now += FIFTEEN_MIN_S
        ts, vals = make_upd(ts_now)
        t_place = time.perf_counter()
        # shard-local ingest boundary: every update array lands as
        # per-shard slices, never a full-array device_put
        upd = tuple(
            assemble_sharded(mesh, a) for a in (rows_np, ts, vals)
        )
        inputs = inputs._replace(
            timestamp_s=np.int32(ts_now), timestamp5_s=np.int32(ts_now)
        )
        t_step = time.perf_counter()
        state, wire = tick_step_wire(state, upd, upd, inputs, cfg)
        np.asarray(wire)  # production sync: packed-wire D2H fetch
        t_done = time.perf_counter()
        if i >= warmup:
            place_s.append(t_step - t_place)
            step_s.append(t_done - t_step)

    wall = np.asarray(place_s) + np.asarray(step_s)
    print(
        json.dumps(
            {
                "n_devices": n_devices,
                "symbols": symbols,
                "window": window,
                "ticks": ticks,
                "wall_ms_per_tick": round(float(np.mean(wall)) * 1000, 3),
                "wall_p50_ms": round(
                    float(np.percentile(wall, 50)) * 1000, 3
                ),
                "wall_p99_ms": round(
                    float(np.percentile(wall, 99)) * 1000, 3
                ),
                "ingest_place_ms": round(
                    float(np.mean(place_s)) * 1000, 3
                ),
                "step_fetch_ms": round(float(np.mean(step_s)) * 1000, 3),
                "mesh": str(dict(mesh.shape)),
            }
        ),
        flush=True,
    )


def run_shard_throughput(
    symbols: int = 2048,
    window: int = 400,
    ticks: int = 24,
    warmup: int = 4,
    counts: tuple = (1, 2, 4, 8),
) -> dict:
    """Virtual-device scaling of the sharded wire step (ISSUE 19).

    One subprocess per device count (XLA fixes the host-platform device
    count at process start), each timing the identical sharded drive via
    :func:`_shard_worker`. The headline is wall speedup at 4 shards vs
    the 1-shard rung; on a host with fewer physical cores than shards the
    CPU model FLOORS the scaling (every virtual device multiplexes onto
    the same cores), so the record carries a measured floor analysis
    attributing where the scaling went instead of a fake speedup — the
    PR 5 precedent. Silicon reruns replace the analysis with the real
    multiplier."""
    import subprocess

    from __graft_entry__ import _subprocess_env

    repo = os.path.dirname(os.path.abspath(__file__))
    sweep: list[dict] = []
    for n in counts:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                (
                    "import bench; bench._shard_worker("
                    f"{int(n)}, {int(symbols)}, {int(window)}, "
                    f"{int(ticks)}, {int(warmup)})"
                ),
            ],
            env=_subprocess_env(n),
            cwd=repo,
            capture_output=True,
            text=True,
            timeout=1800,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"shard worker n={n} failed rc={proc.returncode}:\n"
                + proc.stderr[-2000:]
            )
        line = [
            ln for ln in proc.stdout.splitlines() if ln.startswith("{")
        ][-1]
        rung = json.loads(line)
        sweep.append(rung)
        print(
            f"  shards={n}: {rung['wall_ms_per_tick']} ms/tick "
            f"(ingest {rung['ingest_place_ms']} ms, "
            f"step+fetch {rung['step_fetch_ms']} ms)",
            file=sys.stderr,
        )

    base = sweep[0]["wall_ms_per_tick"]
    for rung in sweep:
        rung["speedup_vs_1shard_x"] = (
            round(base / rung["wall_ms_per_tick"], 3)
            if rung["wall_ms_per_tick"]
            else None
        )
    by_n = {r["n_devices"]: r for r in sweep}
    at4 = by_n.get(4)
    speedup_at_4 = at4["speedup_vs_1shard_x"] if at4 else None
    host_cores = os.cpu_count() or 1

    floor = None
    if (
        speedup_at_4 is not None
        and speedup_at_4 < 1.6
        and host_cores < 4
    ):
        overhead_ms = {
            f"{r['n_devices']}_shards": round(
                r["wall_ms_per_tick"] - base, 3
            )
            for r in sweep[1:]
        }
        floor = {
            "host_physical_cores": host_cores,
            "partition_overhead_ms_vs_1shard": overhead_ms,
            "ingest_place_ms_by_shards": {
                f"{r['n_devices']}_shards": r["ingest_place_ms"]
                for r in sweep
            },
            "note": (
                f"CPU-model floor: this host exposes {host_cores} "
                "physical core(s), so the N virtual devices created by "
                "xla_force_host_platform_device_count all multiplex onto "
                "the same core — the per-shard compute (S/N rows each) "
                "runs SEQUENTIALLY and wall/tick cannot drop below the "
                "1-shard compute time. The sweep therefore measures the "
                "sharding TAX, not the multiplier: wall_n - wall_1 above "
                "is the per-tick cost of the partitioned executable "
                "(GSPMD collectives for the market-context reductions + "
                "wire compaction, per-shard dispatch fan-out, and the "
                "per-shard H2D assembly in ingest_place_ms). The "
                "multiplier needs >= N real cores or chips: per-shard "
                "compute shrinks ~1/N while the measured tax stays "
                "fixed — rerun bench.py --shard-throughput on silicon."
            ),
        }

    return {
        "symbols": symbols,
        "window": window,
        "ticks": ticks,
        "counts": list(counts),
        "sweep": sweep,
        "wall_speedup_at_4_shards_x": speedup_at_4,
        "host_physical_cores": host_cores,
        "cpu_model_floor": floor,
    }


def run_ring_traffic(
    num_symbols: int = 2048, window: int = 400, ticks: int = 64
) -> dict:
    """apply_updates-only scan traffic: cursor ring vs the retired
    shift-append (ISSUE 9 acceptance: >=5x fewer bytes/tick at 2048x400).

    Both arms scan T all-symbol single-bar appends through a jit'd
    ``lax.scan`` with the buffer donated — the exact shape the scanned
    replay's ring update takes, where the cursor layout's one-column
    scatter aliases in place while the shift must move the whole
    (S, W, F) ring every iteration. Bytes come from XLA cost_analysis of
    each compiled scan (per tick = total / T); wall time is a best-of-3
    timed drive as a sanity companion (cost models can lie)."""
    import jax
    import jax.numpy as jnp

    from binquant_tpu.engine.buffer import (
        NUM_FIELDS,
        Field,
        MarketBuffer,
        apply_updates,
        apply_updates_shift,
    )

    S, W, T = num_symbols, window, ticks
    rng = np.random.default_rng(11)
    t0 = 1_753_000_000

    # steady state: a FULL canonical ring (every tick appends one bar per
    # symbol — the replay stream's shape); canonical is required by the
    # shift arm and is a valid ring for the cursor arm
    times = np.broadcast_to(
        t0 + 900 * np.arange(W, dtype=np.int64), (S, W)
    ).astype(np.int32)
    values = rng.random((S, W, NUM_FIELDS), dtype=np.float32)
    buf0 = MarketBuffer(
        times=jnp.asarray(times),
        values=jnp.asarray(values),
        filled=jnp.full((S,), W, jnp.int32),
        cursor=jnp.zeros((S,), jnp.int32),
    )

    rows_seq = np.broadcast_to(
        np.arange(S, dtype=np.int32), (T, S)
    ).copy()
    ts_seq = (
        t0 + 900 * (W + np.arange(T, dtype=np.int64))[:, None]
        + np.zeros((1, S), np.int64)
    ).astype(np.int32)
    vals_seq = rng.random((T, S, NUM_FIELDS), dtype=np.float32)
    vals_seq[:, :, Field.DURATION_S] = 900.0
    seq = (jnp.asarray(rows_seq), jnp.asarray(ts_seq), jnp.asarray(vals_seq))

    def scan_of(update_fn):
        def f(buf, rows, tss, vals):
            def body(b, u):
                return update_fn(b, *u), None

            return jax.lax.scan(body, buf, (rows, tss, vals))[0]

        return jax.jit(f, donate_argnums=(0,))

    def measure(update_fn) -> dict:
        fn = scan_of(update_fn)
        lowered = fn.lower(buf0, *seq)
        compiled = lowered.compile()
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            bytes_per_tick = float(ca.get("bytes accessed", float("nan"))) / T
        except Exception:
            bytes_per_tick = None
        best = None
        for _ in range(3):
            st = jax.tree_util.tree_map(jnp.copy, buf0)
            jax.block_until_ready(st.values)
            t_start = time.perf_counter()
            st = fn(st, *seq)
            jax.block_until_ready(st.times)
            wall = (time.perf_counter() - t_start) / T * 1000.0
            best = wall if best is None else min(best, wall)
        return {
            "bytes_per_tick_mb": (
                None
                if bytes_per_tick is None or bytes_per_tick != bytes_per_tick
                else round(bytes_per_tick / 1e6, 3)
            ),
            "wall_ms_per_tick": round(best, 4),
        }

    cursor = measure(apply_updates)
    shift = measure(apply_updates_shift)

    def _ratio(a, b):
        if not a or not b:
            return None
        return round(a / b, 2)

    return {
        "symbols": S,
        "window": W,
        "ticks": T,
        "cursor_ring": cursor,
        "shift_append": shift,
        # the acceptance number: >=5x fewer apply_updates-only scan bytes
        "bytes_reduction_x": _ratio(
            shift["bytes_per_tick_mb"], cursor["bytes_per_tick_mb"]
        ),
        "wall_reduction_x": _ratio(
            shift["wall_ms_per_tick"], cursor["wall_ms_per_tick"]
        ),
        "measurement": (
            "T single-bar all-symbol appends scanned through one jit'd "
            "lax.scan per arm, buffer donated (steady-state aliasing); "
            "bytes from XLA cost_analysis / T, wall best-of-3"
        ),
        "measurement_epoch": MEASUREMENT_EPOCH,
    }


def run_replay_throughput(
    num_symbols: int = 2048,
    window: int = 400,
    ticks: int = 256,
    scan_chunk: int = 64,
) -> dict:
    """Replay/backtest throughput: serial per-tick drive vs fused scan
    chunks (ISSUE 5 acceptance phase).

    Both arms drive the PRODUCTION engine over the identical synthetic
    stream (same seed → same updates): the serial arm is the per-tick
    ``process_tick`` loop every multi-tick lane used to run (one Python
    iteration + one device dispatch per tick, depth 0 — the replay/refdiff
    shape); the scanned arm is ``process_ticks_scanned`` (one ``lax.scan``
    dispatch per ``scan_chunk`` ticks). Warmup ticks run each arm's full
    compile set (cold-start full tick, per-tick incremental step, the scan
    executable) before the measured window, so the quoted ticks/sec is
    steady-state — the regime a months-of-candles backtest amortizes into.
    Candles/sec counts every ingested bar (two intervals per tick)."""
    import os

    # the scanned drive requires the incremental path; the serial arm runs
    # the live default pair (incremental + donated dispatch)
    os.environ.setdefault("BQT_INCREMENTAL", "1")
    os.environ.setdefault("BQT_DONATE", "1")

    def drive_arm(scanned: bool) -> dict:
        from binquant_tpu.obs.latency import PhaseAccountant

        engine, make_updates, now, px = _seed_engine(num_symbols, window, 0)
        engine.scan_chunk = scan_chunk
        # host-phase dwell accounting (ISSUE 11): pinned ON regardless of
        # the ambient env so the record always carries the breakdown;
        # reset after warmup so compiles don't pollute the steady state
        engine.host_phase = PhaseAccountant(enabled=True)
        px_box = [px]

        def feed(i: int) -> int:
            eval_s = now + i * 900
            rows, ts15, vals15, px2 = make_updates(eval_s - 900, px_box[0], 900)
            engine.batcher15.add_batch(rows, ts15, vals15)
            rows5, ts5, vals5, _ = make_updates(eval_s - 300, px2, 300)
            engine.batcher5.add_batch(rows5, ts5, vals5)
            px_box[0] = px2
            return eval_s * 1000

        # warm every executable the measured window will hit: the cold
        # full-recompute tick, the per-tick incremental step (serial arm +
        # the scanned drive's short-run/overflow re-drives), and — for the
        # scanned arm — one full scan chunk
        warmup = (scan_chunk + 4) if scanned else 4
        signals = 0

        async def run_arm() -> float:
            nonlocal signals
            if scanned:

                def tick_item(i):
                    # feed at PLAN time: now_ms must be computed eagerly,
                    # batcher loads lazily in drive order
                    eval_ms = (now + i * 900) * 1000
                    return (eval_ms, lambda i=i: feed(i))

                signals += len(
                    await engine.process_ticks_scanned(
                        [tick_item(i) for i in range(warmup)]
                    )
                )
                await engine.flush_pending()
                engine.host_phase.reset()
                t0 = time.perf_counter()
                signals += len(
                    await engine.process_ticks_scanned(
                        [tick_item(warmup + i) for i in range(ticks)]
                    )
                )
                await engine.flush_pending()
                return time.perf_counter() - t0
            for i in range(warmup):
                now_ms = feed(i)
                signals += len(await engine.process_tick(now_ms=now_ms))
            signals += len(await engine.flush_pending())
            engine.host_phase.reset()
            t0 = time.perf_counter()
            for i in range(ticks):
                now_ms = feed(warmup + i)
                signals += len(await engine.process_tick(now_ms=now_ms))
            signals += len(await engine.flush_pending())
            return time.perf_counter() - t0

        wall = asyncio.run(run_arm())
        return {
            "host_phase": engine.host_phase.snapshot(),
            "wall_s": round(wall, 3),
            "ticks": ticks,
            "ticks_per_sec": round(ticks / wall, 2),
            # one 5m + one 15m bar per symbol per tick
            "candles_per_sec": round(ticks * num_symbols * 2 / wall),
            "per_tick_ms": round(wall / ticks * 1000.0, 3),
            "signals": signals,
            "scan_chunks": engine.scan_chunks,
            "scanned_ticks": engine.scanned_ticks,
            "scan_overflow_reruns": engine.scan_overflow_reruns,
            "donated_ticks": engine.donated_ticks,
        }

    serial = drive_arm(scanned=False)
    scanned = drive_arm(scanned=True)
    speedup = (
        round(scanned["ticks_per_sec"] / serial["ticks_per_sec"], 2)
        if serial["ticks_per_sec"]
        else None
    )

    # host-phase breakdown (ISSUE 11): the tracked regression surface for
    # ROADMAP item 3 — "the scanned drive's UNOVERLAPPED host work exceeds
    # the dispatch overhead it amortizes" becomes machine-readable numbers
    # instead of a one-off floor analysis
    def _per_tick(arm: dict, drive: str) -> dict:
        phases = arm.get("host_phase", {}).get("phase_ms", {}).get(drive, {})
        return {p: round(v["total_ms"] / ticks, 3) for p, v in phases.items()}

    serial_phase = _per_tick(serial, "serial")
    scanned_phase = _per_tick(scanned, "scanned")
    host_keys = ("plan", "stack", "decode", "emit")
    scanned_host = round(sum(scanned_phase.get(k, 0.0) for k in host_keys), 3)
    serial_dispatch = round(serial_phase.get("dispatch", 0.0), 3)
    host_phase_section = {
        "serial_ms_per_tick": serial_phase,
        "scanned_ms_per_tick": scanned_phase,
        "scanned_unoverlapped_host_ms_per_tick": scanned_host,
        "serial_dispatch_overhead_ms_per_tick": serial_dispatch,
        "scanned_host_exceeds_serial_dispatch": scanned_host > serial_dispatch,
        "serial_occupancy": serial.get("host_phase", {})
        .get("occupancy", {})
        .get("serial"),
        "scanned_occupancy": scanned.get("host_phase", {})
        .get("occupancy", {})
        .get("scanned"),
        "note": (
            "per-tick host-phase dwell over the measured window "
            "(steady state, compiles reset after warmup); phases: "
            "plan/stack/dispatch/device_wait/decode/emit per "
            "obs/latency.py. scanned_unoverlapped_host = plan+stack+"
            "decode+emit — the work the host-overlap pipeline (ROADMAP "
            "item 3) must hide behind the device dispatch. The serial "
            "occupancy's large dead_gap is the ASYNC device compute "
            "overlapping host boundaries (verified: synchronous CPU "
            "dispatch moves it into the dispatch bracket), so serial "
            "host cost is the bracketed host_ms, not wall - device."
        ),
    }

    return {
        "symbols": num_symbols,
        "window": window,
        "ticks": ticks,
        "scan_chunk": scan_chunk,
        "serial": serial,
        "scanned": scanned,
        "scanned_vs_serial_x": speedup,
        "host_phase": host_phase_section,
        # ISSUE 17: the scanned drive's share of the decode vectorization
        # (per-tick unpack_wire loop vs the one-pass unpack_wire_block the
        # chunk flush now uses) — kernel stages are backtest-only levers
        "decode_attribution": backtest_stage_attribution(
            num_symbols, window, scan_chunk, include_kernels=False
        ),
        "measurement": (
            "production SignalEngine over one synthetic stream per arm "
            "(identical seeds): serial = per-tick process_tick at depth 0 "
            "(the pre-ISSUE-5 replay drive); scanned = "
            "process_ticks_scanned lax.scan chunks. Steady-state: all "
            "compiles paid in warmup. CPU-model numbers — rerun on "
            "silicon when the tunnel returns."
        ),
        "cpu_model_floor_note": (
            "ISSUE-9 floor analysis, post-cursor-ring: the physical ring "
            "shift (~144 MB/tick at 2048x400) that used to floor BOTH "
            "drives is gone — the SERIAL per-tick drive collapsed ~4x "
            "(~120 -> ~32 ms/tick; donated incremental step ~22 ms) "
            "because it paid the shift on every dispatch, while the scan "
            "body (now ~18 ms/tick at T=64) only amortized dispatch "
            "overhead the shift never dominated. On this CPU model the "
            "scanned drive's UNOVERLAPPED host work (chunk planning, "
            "input stacking, a chunk's back-to-back finalizes after one "
            "long blocking dispatch) now exceeds the dispatch overhead "
            "it erases, so scanned-vs-serial can read < 1x at production "
            "shape and ~1.9x at the dispatch-bound point. The ratio's "
            "denominator moved, not the scan: absolute replay throughput "
            "ROSE (best drive 92k -> ~129k candles/s, now the serial "
            "loop). The scan remains the dispatch-amortization lever for "
            "high-RTT (tunneled/remote) devices — rerun "
            "bench.py --replay-throughput on silicon."
        ),
        "measurement_epoch": MEASUREMENT_EPOCH,
    }


def backtest_stage_attribution(
    num_symbols: int = 512,
    window: int = 240,
    chunk: int = 12,
    reps: int = 3,
    include_kernels: bool = True,
) -> dict:
    """Per-stage precompute attribution (ISSUE 17): each position-local
    stage of the backtest chunk body timed in its BEFORE form (per-tick
    ``vmap`` over gathered ``(T, S, W)`` window views) against its AFTER
    form (one extension-invariant pass over the ``(S, W+T)`` extension),
    plus the host wire decode (per-tick ``unpack_wire`` loop vs the
    one-pass ``unpack_wire_block``). Synthetic full-history buffers at
    the bench shape; numbers are wall ms per chunk-equivalent call, best
    of ``reps`` after a compile/warm rep."""
    import jax
    import jax.numpy as jnp

    from binquant_tpu.backtest.kernel import _window_views
    from binquant_tpu.engine.buffer import NUM_FIELDS, Field
    from binquant_tpu.engine.step import BC_WINDOW
    from binquant_tpu.ops.indicators import log_returns, rolling_beta_corr
    from binquant_tpu.regime.context import (
        compute_symbol_features,
        compute_symbol_features_ext,
    )
    from binquant_tpu.strategies.features import (
        compute_feature_pack,
        compute_feature_pack_ext,
        ext_gather,
    )

    S, W, T = num_symbols, window, chunk
    L = W + T
    rng = np.random.default_rng(11)
    stages: dict = {}
    if not include_kernels:
        # decode-only attribution (the scanned drive's lever): skip the
        # backtest-kernel stages, keep the host wire-decode rows below
        return _finish_stage_attribution(S, W, T, reps, rng, stages)
    t0 = 1_700_000_000
    times = np.broadcast_to(
        t0 + (np.arange(L, dtype=np.int64) - (W - 1)) * 900, (S, L)
    ).astype(np.int32)
    close = (
        100.0 * np.exp(np.cumsum(rng.normal(0.0, 0.01, (S, L)), axis=1))
    ).astype(np.float32)
    vals = np.zeros((S, L, NUM_FIELDS), np.float32)
    vals[:, :, Field.OPEN] = np.roll(close, 1, axis=1)
    vals[:, :, Field.HIGH] = close * 1.01
    vals[:, :, Field.LOW] = close * 0.99
    vals[:, :, Field.CLOSE] = close
    vals[:, :, Field.VOLUME] = (
        rng.random((S, L)).astype(np.float32) * 100.0 + 1.0
    )
    vals[:, :, Field.QUOTE_VOLUME] = vals[:, :, Field.VOLUME] * close
    vals[:, :, Field.NUM_TRADES] = 50.0
    vals[:, :, Field.TAKER_BUY_BASE] = vals[:, :, Field.VOLUME] * 0.5
    vals[:, :, Field.TAKER_BUY_QUOTE] = vals[:, :, Field.QUOTE_VOLUME] * 0.5
    vals[:, :, Field.DURATION_S] = 900.0
    et = jnp.asarray(times)
    ev = jnp.asarray(vals)
    cn = jnp.asarray(
        np.tile(np.arange(1, T + 1, dtype=np.int32)[:, None], (1, S))
    )
    f0 = jnp.asarray(np.full((S,), W, np.int32))
    eligible = jnp.ones((T, S), bool)

    def best_ms(fn, *a) -> float:
        jax.block_until_ready(fn(*a))  # compile + warm
        best = float("inf")
        for _ in range(max(reps, 1)):
            s = time.perf_counter()
            jax.block_until_ready(fn(*a))
            best = min(best, (time.perf_counter() - s) * 1000.0)
        return round(best, 2)

    # the (T, S, W, F) gather the vmapped path materializes once per chunk
    # and every view-consuming stage reads; the ext path eliminates it
    gather = jax.jit(lambda et, ev, cn, f0: _window_views(et, ev, cn, f0, W))
    views = jax.block_until_ready(gather(et, ev, cn, f0))

    packs_before = jax.jit(lambda v: jax.vmap(compute_feature_pack)(v))
    packs_after = jax.jit(
        lambda et, ev, cn, f0: compute_feature_pack_ext(et, ev, cn, f0, W)
    )
    feats_before = jax.jit(
        lambda v, el: jax.vmap(compute_symbol_features)(v, el)
    )
    feats_after = jax.jit(
        lambda et, ev, cn, f0, el: compute_symbol_features_ext(
            et, ev, cn, f0, W, el
        )
    )

    def _bc_before(v):
        close = v.values[:, :, :, Field.CLOSE]

        def one(c):
            rets = log_returns(c)
            bc = rolling_beta_corr(rets, rets[0][None, :], window=BC_WINDOW)
            return bc.beta[:, -1], bc.corr[:, -1]

        return jax.vmap(one)(close)

    def _bc_after(ev, cn):
        close = ev[:, :, Field.CLOSE]
        rets = log_returns(close)
        bc = rolling_beta_corr(rets, rets[0][None, :], window=BC_WINDOW)
        last = (cn + (W - 1)).astype(jnp.int32)
        return ext_gather(bc.beta, last), ext_gather(bc.corr, last)

    stages = {
        "view_gather": {
            "before_ms": best_ms(gather, et, ev, cn, f0),
            # the ext kernels read the (S, L) extension directly
            "after_ms": 0.0,
        },
        "packs": {
            "before_ms": best_ms(packs_before, views),
            "after_ms": best_ms(packs_after, et, ev, cn, f0),
        },
        "feats": {
            "before_ms": best_ms(feats_before, views, eligible),
            "after_ms": best_ms(feats_after, et, ev, cn, f0, eligible),
        },
        "betacorr": {
            "before_ms": best_ms(jax.jit(_bc_before), views),
            "after_ms": best_ms(jax.jit(_bc_after), ev, cn),
        },
    }

    return _finish_stage_attribution(S, W, T, reps, rng, stages)


def _finish_stage_attribution(
    S: int, W: int, T: int, reps: int, rng, stages: dict
) -> dict:
    """Shared tail of :func:`backtest_stage_attribution`: the host wire
    decode rows (per-tick ``unpack_wire`` loop vs ``unpack_wire_block``
    on synthetic full-layout wires — same construction the batch-decode
    parity test pins) plus the record envelope."""
    from binquant_tpu.engine.step import (
        WIRE_FIRED_COUNT_OFF,
        WIRE_MAX_FIRED,
        unpack_wire,
        unpack_wire_block,
        wire_length,
    )

    Lw = wire_length(S, numeric_digest=True, ingest_digest=True)
    w = rng.random((T, Lw)).astype(np.float32) * 4.0
    off, K = WIRE_FIRED_COUNT_OFF, WIRE_MAX_FIRED
    for t in range(T):
        w[t, off] = 5.0
        blocks = w[t, off + 1 : off + 1 + 6 * K].reshape(6, K)
        blocks[0] = rng.integers(0, 8, K)
        blocks[1] = rng.integers(0, S, K)

    def best_wall(fn) -> float:
        fn()
        best = float("inf")
        for _ in range(max(reps, 1)):
            s = time.perf_counter()
            fn()
            best = min(best, (time.perf_counter() - s) * 1000.0)
        return round(best, 3)

    stages["decode"] = {
        "before_ms": best_wall(
            lambda: [
                unpack_wire(w[t], numeric_digest=True, ingest_digest=True)
                for t in range(T)
            ]
        ),
        "after_ms": best_wall(
            lambda: unpack_wire_block(
                w, numeric_digest=True, ingest_digest=True
            )
        ),
    }

    return {
        "shape": {"symbols": S, "window": W, "chunk": T},
        "stages_ms_per_chunk": stages,
        "note": (
            "per-stage wall per chunk-equivalent call, best of N after a "
            "warm rep. 'before' = the per-tick vmapped form over gathered "
            "(T,S,W) window views (views pre-materialized; the gather "
            "itself is the view_gather row), 'after' = the "
            "extension-invariant single pass over (S,W+T) "
            "(BQT_EXT_INVARIANT=1). packs/feats rows time ONE interval; "
            "the chunk body runs two (5m+15m). decode rows are host "
            "numpy/python wall on synthetic full-layout wires (numeric + "
            "ingest digest slabs on, 5 fired/tick)."
        ),
    }


def run_backtest_throughput(
    num_symbols: int = 512,
    window: int = 240,
    ticks: int = 96,
    backtest_chunk: int = 12,
    best_of: int = 3,
    sweep_combos: int = 64,
) -> dict:
    """Backtest throughput (ISSUE 6 acceptance): serial full-recompute
    drive vs the time-batched ``(S, W+T)`` backend over identical streams,
    plus a vmapped ≥64-combo parameter-grid arm.

    Both engine arms run FULL-recompute semantics (incremental off) — the
    backend's contract. Each arm runs ``best_of`` times and quotes its
    best run: this box carries intermittent neighbor load, so a single
    sample under-reports (the arms run strictly serialized, never
    concurrently). Candles/sec counts every ingested bar (two intervals
    per tick); the sweep arm additionally quotes combo-candles/sec =
    P × candles/sec — the hyperparameter-search workload's true rate."""

    def drive_arm(backtest: bool, ext: bool = False) -> dict:
        from binquant_tpu.obs.latency import PhaseAccountant

        best = None
        for _rep in range(max(best_of, 1)):
            engine, make_updates, now, px = _seed_engine(
                num_symbols, window, 0, incremental=False
            )
            engine.backtest_chunk = backtest_chunk
            if ext:
                # extension-invariant precompute (BQT_EXT_INVARIANT=1):
                # the margin-governed twin of the vmapped chunk body
                engine.ext_invariant = True
            # host-phase dwell pinned ON (ISSUE 11), reset after warmup
            engine.host_phase = PhaseAccountant(enabled=True)
            px_box = [px]

            def feed(i: int, engine=engine, make_updates=make_updates,
                     now=now, px_box=px_box) -> int:
                eval_s = now + i * 900
                rows, ts15, vals15, px2 = make_updates(
                    eval_s - 900, px_box[0], 900
                )
                engine.batcher15.add_batch(rows, ts15, vals15)
                rows5, ts5, vals5, _ = make_updates(eval_s - 300, px2, 300)
                engine.batcher5.add_batch(rows5, ts5, vals5)
                px_box[0] = px2
                return eval_s * 1000

            warmup = (backtest_chunk + 4) if backtest else 4
            signals = 0

            async def run_arm(engine=engine, feed=feed,
                              warmup=warmup) -> float:
                nonlocal signals
                if backtest:

                    def tick_item(i):
                        eval_ms = (now + i * 900) * 1000
                        return (eval_ms, lambda i=i: feed(i))

                    signals += len(
                        await engine.process_ticks_backtest(
                            [tick_item(i) for i in range(warmup)]
                        )
                    )
                    await engine.flush_pending()
                    engine.host_phase.reset()
                    t0 = time.perf_counter()
                    signals += len(
                        await engine.process_ticks_backtest(
                            [tick_item(warmup + i) for i in range(ticks)]
                        )
                    )
                    await engine.flush_pending()
                    return time.perf_counter() - t0
                for i in range(warmup):
                    now_ms = feed(i)
                    signals += len(await engine.process_tick(now_ms=now_ms))
                signals += len(await engine.flush_pending())
                engine.host_phase.reset()
                t0 = time.perf_counter()
                for i in range(ticks):
                    now_ms = feed(warmup + i)
                    signals += len(await engine.process_tick(now_ms=now_ms))
                signals += len(await engine.flush_pending())
                return time.perf_counter() - t0

            wall = asyncio.run(run_arm())
            arm = {
                "host_phase": engine.host_phase.snapshot(),
                "wall_s": round(wall, 3),
                "ticks": ticks,
                "ticks_per_sec": round(ticks / wall, 2),
                "candles_per_sec": round(ticks * num_symbols * 2 / wall),
                "per_tick_ms": round(wall / ticks * 1000.0, 3),
                "signals": signals,
                "backtest_chunks": engine.backtest_chunks,
                "backtest_ticks": engine.backtest_ticks,
                "backtest_overflow_reruns": engine.backtest_overflow_reruns,
            }
            if best is None or arm["ticks_per_sec"] > best["ticks_per_sec"]:
                best = arm
        best["best_of"] = best_of
        return best

    serial = drive_arm(backtest=False)
    batched = drive_arm(backtest=True)
    batched_ext = drive_arm(backtest=True, ext=True)
    winner_name = (
        "ext"
        if batched_ext["ticks_per_sec"] > batched["ticks_per_sec"]
        else "default"
    )
    winner = batched_ext if winner_name == "ext" else batched
    # headline = best batched arm vs the serial full drive (the default
    # arm's ratio is kept alongside — the bit-identical path's own number)
    speedup = (
        round(winner["ticks_per_sec"] / serial["ticks_per_sec"], 2)
        if serial["ticks_per_sec"]
        else None
    )
    default_speedup = (
        round(batched["ticks_per_sec"] / serial["ticks_per_sec"], 2)
        if serial["ticks_per_sec"]
        else None
    )
    ext_vs_default = (
        round(batched_ext["ticks_per_sec"] / batched["ticks_per_sec"], 2)
        if batched["ticks_per_sec"]
        else None
    )

    # --- depth-2 pipelining verdict (ISSUE 17 satellite): with the chunk
    # decode vectorized, does the winning arm's UNOVERLAPPED host work
    # still exceed the dispatch+device time a depth-2 overlap could hide
    # it behind? Verdict only — the overlap itself is NOT built here.
    def _phase_per_tick(arm: dict, drive: str = "backtest") -> dict:
        phases = arm.get("host_phase", {}).get("phase_ms", {}).get(drive, {})
        return {p: round(v["total_ms"] / ticks, 3) for p, v in phases.items()}

    win_phase = _phase_per_tick(winner)
    host_ms = round(
        sum(win_phase.get(k, 0.0) for k in ("plan", "stack", "decode", "emit")),
        3,
    )
    overhead_ms = round(
        win_phase.get("dispatch", 0.0) + win_phase.get("device_wait", 0.0), 3
    )
    pipelining_verdict = {
        "arm": winner_name,
        "phase_ms_per_tick": win_phase,
        "unoverlapped_host_ms_per_tick": host_ms,
        "dispatch_plus_device_wait_ms_per_tick": overhead_ms,
        "depth2_pipelining_worth_it": host_ms > overhead_ms,
        "note": (
            "post-decode-vectorization host_phase re-measure: "
            "unoverlapped host = plan+stack+decode+emit per tick on the "
            "winning batched arm; a depth-2 chunk pipeline (decode chunk "
            "k while chunk k+1 computes) can hide at most "
            "min(host, dispatch+device_wait) of it, so it is only worth "
            "building when host > dispatch+device_wait. Verdict recorded, "
            "pipeline deliberately not built (ISSUE 17)."
        ),
    }

    # --- per-stage precompute attribution: vmapped-views vs ext forms at
    # the bench shape (packs/feats/betacorr/view-gather) + host decode
    attribution = backtest_stage_attribution(
        num_symbols, window, backtest_chunk, reps=max(best_of, 1)
    )

    # --- vmapped parameter-grid arm: one dispatch scores the whole grid
    from binquant_tpu.backtest import run_param_sweep
    from binquant_tpu.io.replay import generate_replay_file

    import math
    import tempfile

    side = max(2, round(sweep_combos ** (1.0 / 3.0)))
    axes = {
        "pt.rsi_oversold": list(np.linspace(15.0, 60.0, side)),
        "mrf.rsi_long_max": list(np.linspace(10.0, 40.0, side)),
        "abp.volume_multiplier": list(
            np.linspace(1.5, 6.0, math.ceil(sweep_combos / side / side))
        ),
    }
    sweep_best = None
    with tempfile.TemporaryDirectory() as td:
        sweep_path = f"{td}/sweep.jsonl"
        sweep_syms, sweep_ticks = 48, 96
        generate_replay_file(
            sweep_path, n_symbols=sweep_syms, n_ticks=sweep_ticks
        )
        for _rep in range(max(best_of, 1)):
            r = run_param_sweep(
                sweep_path,
                axes=axes,
                capacity=sweep_syms,
                window=window,
                chunk=sweep_ticks + 8,  # whole stream in ONE dispatch
                # scoring off: the throughput arm quotes the pre-scoring
                # graph (fired-slot slice never computed) — the outcome
                # bed's own cost is the --outcome-cost arm
                horizons=None,
            )
            if (
                sweep_best is None
                or (r["combo_candles_per_sec"] or 0)
                > (sweep_best["combo_candles_per_sec"] or 0)
            ):
                sweep_best = r
    sweep_summary = {
        "P": sweep_best["P"],
        "dispatches": sweep_best["dispatches"],
        "evaluated_ticks": sweep_best["evaluated_ticks"],
        "candles": sweep_best["candles"],
        "wall_s": sweep_best["wall_s"],
        "combo_candles_per_sec": sweep_best["combo_candles_per_sec"],
        "distinct_fire_totals": len(set(sweep_best["total_fired"])),
        "best_of": best_of,
        "axes": sweep_best["axes"],
    }

    return {
        "symbols": num_symbols,
        "window": window,
        "ticks": ticks,
        "backtest_chunk": backtest_chunk,
        "serial_full": serial,
        "backtest": batched,
        "backtest_ext": batched_ext,
        "backtest_winner": winner_name,
        "backtest_vs_serial_x": speedup,
        "backtest_default_vs_serial_x": default_speedup,
        "backtest_ext_vs_default_x": ext_vs_default,
        "precompute_attribution": attribution,
        "pipelining_verdict": pipelining_verdict,
        "param_sweep": sweep_summary,
        "measurement": (
            "production SignalEngine over one synthetic stream per arm "
            "(identical seeds), all arms full-recompute "
            "(BQT_INCREMENTAL=0): serial = per-tick process_tick at depth "
            "0; backtest = process_ticks_backtest (S, W+T) chunks "
            "(bit-identical default precompute); backtest_ext = the same "
            "drive with BQT_EXT_INVARIANT=1 (extension-invariant "
            "precompute, margin-governed — see README §Backtest). "
            "Headline backtest_vs_serial_x quotes the faster batched arm "
            "(backtest_winner). Steady-state (compiles in warmup), "
            "best-of-N serialized runs (neighbor noise). Sweep arm: "
            f"run_param_sweep over a {sweep_combos}-combo grid, whole "
            "stream per dispatch. CPU-model numbers — rerun on silicon "
            "when the tunnel returns."
        ),
        "measurement_epoch": MEASUREMENT_EPOCH,
    }


def _rtt_probe(iters: int = 15) -> tuple[float, float]:
    """Round-trip tax of the device link: tiny jit + blocking 4-byte fetch.

    Through the axon tunnel this is ~150 ms median with heavy tail; on a
    local chip ~0.1 ms. Returns (median, p99-ish max). The serial e2e
    numbers are dominated by one blocking D2H leg, so the untunneled
    projection subtracts the probe — tail-vs-tail (e2e_p99 − rtt_max) for
    the p99 projection, since the tunnel's variance is the dominant
    variance on both sides.
    """
    import jax

    tiny = jax.jit(lambda x: x + 1)
    arr = jax.device_put(np.zeros(1, np.float32))
    np.asarray(tiny(arr))  # compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        np.asarray(tiny(arr))
        times.append((time.perf_counter() - t0) * 1000.0)
    return float(np.median(times)), float(np.max(times))


def run(
    num_symbols: int, window: int, ticks: int, warmup: int, depth: int = 6
) -> dict:
    from binquant_tpu.io.metrics import LatencyTracker

    rtt_ms, rtt_max_ms = _rtt_probe()
    engine, make_updates, now, px = _seed_engine(num_symbols, window, depth)

    def feed(i: int, px):
        """Queue one closed 15m bar + one closed 5m bar per symbol for the
        tick evaluated at ``now + i*900`` (open times one interval behind,
        exactly what process_tick's freshness masks check)."""
        eval_s = now + i * 900
        rows, ts15, vals15, px = make_updates(eval_s - 900, px, 900)
        engine.batcher15.add_batch(rows, ts15, vals15)
        rows, ts5, vals5, _ = make_updates(eval_s - 300, px, 300)
        engine.batcher5.add_batch(rows, ts5, vals5)
        return eval_s * 1000, px

    async def drive() -> dict:
        nonlocal px
        # compile + warm through the production path — including the
        # finalize side (wire fetch + extraction), which otherwise only
        # runs ``depth`` ticks in and would pay its lazy compiles inside
        # the measured phase
        for i in range(max(warmup, 1)):
            now_ms, px = feed(i, px)
            await engine.process_tick(now_ms=now_ms)
        await engine.flush_pending()
        assert engine.ticks_processed >= 1

        # --- phase 1 (headline): pipelined back-to-back
        import gc

        engine.latency = LatencyTracker(mirror=False)  # bench: keep the global histogram clean
        gc.collect()
        gc.disable()
        base = warmup
        for i in range(ticks):
            now_ms, px = feed(base + i, px)
            await engine.process_tick(now_ms=now_ms)
        await engine.flush_pending()
        gc.enable()
        pipelined = engine.latency.stats()

        # --- phase 2 (HEADLINE): depth-1 at the production 1 s cadence —
        # exactly main.py's consume_loop shape. The wire lands during the
        # idle second, so tick_total is the honest per-tick cost of the
        # live engine (BASELINE: 2000 symbols @ 1 s ticks, p99 < 50 ms).
        engine.pipeline_depth = 1
        await engine.flush_pending()
        engine.latency = LatencyTracker(mirror=False)  # bench: keep the global histogram clean
        base += ticks
        paced_ticks = min(max(ticks // 2, 10), 180)
        for i in range(paced_ticks):
            now_ms, px = feed(base + i, px)
            await engine.process_tick(now_ms=now_ms)
            await asyncio.sleep(1.0)
        await engine.flush_pending()
        paced = engine.latency.stats()

        # --- phase 2b: depth-1 WITH the fired-tick fast path (the actual
        # consume_loop shape): emit_ready lands + emits each tick's wire
        # ~one device round trip after dispatch instead of waiting out the
        # cadence. Measures SIGNAL latency (dispatch→emit, candle→emit) —
        # the number a trading system cares about (VERDICT r3 item 3).
        engine.latency = LatencyTracker(mirror=False)  # bench: keep the global histogram clean
        base += paced_ticks
        early_ticks = min(max(ticks // 4, 10), 60)
        for i in range(early_ticks):
            now_ms, px = feed(base + i, px)
            t0 = time.perf_counter()
            await engine.process_tick(now_ms=now_ms)
            if engine._pending:
                await engine.emit_ready()
            await asyncio.sleep(max(0.0, 1.0 - (time.perf_counter() - t0)))
        await engine.flush_pending()
        early = engine.latency.stats()
        base += early_ticks

        # --- phase 3: serial e2e (depth 0 — full round trip per tick)
        engine.pipeline_depth = 0
        engine.latency = LatencyTracker(mirror=False)  # bench: keep the global histogram clean
        for i in range(min(max(ticks // 10, 5), 23)):
            now_ms, px = feed(base + i, px)
            await engine.process_tick(now_ms=now_ms)
        serial = engine.latency.stats()
        return {
            "pipelined": pipelined,
            "paced": paced,
            "early": early,
            "serial": serial,
        }

    stats = asyncio.run(drive())
    paced = stats["paced"]["tick_total"]
    throughput = stats["pipelined"]["tick_total"]
    early = stats["early"]
    # absent stage (e.g. no signal fired in a phase) -> None, which
    # serializes as JSON null; float('nan') would emit invalid JSON
    nan = {"p50_ms": None, "p99_ms": None}
    return {
        # headline: the live-cadence shape
        "p50_ms": paced["p50_ms"],
        "p99_ms": paced["p99_ms"],
        "mean_ms": paced["mean_ms"],
        # back-to-back pipelined: device-throughput stress (no idle gap)
        "throughput_p50_ms": throughput["p50_ms"],
        "throughput_p99_ms": throughput["p99_ms"],
        "e2e_p50_ms": stats["serial"]["tick_total"]["p50_ms"],
        "e2e_p99_ms": stats["serial"]["tick_total"]["p99_ms"],
        "device_dispatch_p99_ms": stats["paced"]["device_dispatch"]["p99_ms"],
        "wire_fetch_p99_ms": stats["paced"]["wire_fetch"]["p99_ms"],
        # signal latency (fired-tick fast path, the consume_loop shape):
        # dispatch→emit is the pipelining lag actually paid; candle→emit
        # adds bar staleness at dispatch. serial_lag_* quote depth 0.
        "signal_lag_p50_ms": early.get("dispatch_to_emit", nan)["p50_ms"],
        "signal_lag_p99_ms": early.get("dispatch_to_emit", nan)["p99_ms"],
        "candle_to_emit_p50_ms": early.get("candle_to_emit", nan)["p50_ms"],
        "candle_to_emit_p99_ms": early.get("candle_to_emit", nan)["p99_ms"],
        "classic_lag_p99_ms": stats["paced"].get("dispatch_to_emit", nan)[
            "p99_ms"
        ],
        "serial_lag_p99_ms": stats["serial"].get("dispatch_to_emit", nan)[
            "p99_ms"
        ],
        "rtt_probe_ms": rtt_ms,
        "rtt_probe_max_ms": rtt_max_ms,
        # untunneled-chip projections of the serial (depth-0) path:
        # median-vs-median and tail-vs-tail (the tunnel's tail dominates
        # both sides, so subtracting matched quantiles is the honest
        # estimate; VERDICT r4 criterion: p99 projection <= 50 ms)
        # floored at 0: a negative difference just means the tunnel's
        # variance swamped the device+host cost entirely
        "serial_projection_p50_ms": max(
            0.0, float(stats["serial"]["tick_total"]["p50_ms"] - rtt_ms)
        ),
        "serial_projection_p99_ms": max(
            0.0, float(stats["serial"]["tick_total"]["p99_ms"] - rtt_max_ms)
        ),
        # sustained soak rate: back-to-back pipelined ticks, no idle gap
        "ticks_per_sec": float(1000.0 / throughput["mean_ms"]),
        # basis: the ENABLED live set (the wire path compiles only those
        # kernels since round 5 — dormant kernels are no longer computed
        # per tick; full-capability throughput is the device breakdown's
        # full_evals_per_sec)
        "evals_basis_strategies": len(engine._wire_enabled_key()),
        "symbol_evals_per_sec": float(
            num_symbols
            * len(engine._wire_enabled_key())
            / (throughput["mean_ms"] / 1000.0)
        ),
        # one stage table PER MEASUREMENT PATH (VERDICT r4 weak #4): the
        # classic paced path and the early-emit (fired-tick fast path)
        # never share a key, so e.g. candle_to_emit cannot be read off the
        # wrong path
        "stage_p99_ms": {
            "paced_classic": {
                k: v["p99_ms"] for k, v in sorted(stats["paced"].items())
            },
            "early_emit": {
                k: v["p99_ms"] for k, v in sorted(stats["early"].items())
            },
        },
    }


def run_config4(
    num_symbols: int, window: int, ticks: int, warmup: int, depth: int = 6
) -> dict:
    """BASELINE config #4: context scoring across all symbols × 4 timeframes.

    Four timeframe buffers (1m/5m/15m/1h) each get a full market-context
    build (symbol features → aggregates → regime ladders) plus the
    direction-vectorized signal-context scorer over every symbol, all in
    one jit'd step — the batched equivalent of the reference running
    ``market_regime/context_scoring.py`` per symbol per timeframe.

    Two measured phases (VERDICT r2 item 7 — round 2 only measured the
    first): **fresh-bar** ticks append one new bar per timeframe and build
    the context at the advanced timestamp (the steady-state cost every
    bucket boundary pays — buffer scatter + feature rebuild + carry
    promotion), and **refinement** ticks re-evaluate the same timestamp
    with no new bars (the mid-bucket path). The headline quotes the
    costlier fresh-bar number.
    """
    import jax
    import jax.numpy as jnp

    from binquant_tpu.engine.buffer import (
        NUM_FIELDS,
        Field,
        apply_updates,
        empty_buffer,
        fresh_mask,
    )
    from binquant_tpu.regime.context import (
        ContextConfig,
        compute_market_context,
        initial_regime_carry,
    )
    from binquant_tpu.regime.scoring import score_signal_candidate

    rng = np.random.default_rng(11)
    cfg = ContextConfig()
    TIMEFRAMES = (60, 300, 900, 3600)
    t0 = 1_753_000_200 - 1_753_000_200 % 3600
    px = 20.0 + rng.random(num_symbols).astype(np.float32) * 100

    def updates(ts_s, px, dur):
        closes = px * (1 + rng.normal(0, 0.004, num_symbols))
        vals = np.zeros((num_symbols, NUM_FIELDS), dtype=np.float32)
        vals[:, Field.OPEN] = px
        vals[:, Field.CLOSE] = closes
        vals[:, Field.HIGH] = np.maximum(px, closes) * 1.002
        vals[:, Field.LOW] = np.minimum(px, closes) * 0.998
        vals[:, Field.VOLUME] = np.abs(rng.normal(1000, 150, num_symbols))
        vals[:, Field.DURATION_S] = dur
        rows = np.arange(num_symbols, dtype=np.int32)
        return rows, np.full(num_symbols, ts_s, np.int32), vals, closes

    bufs, carries, pxs = [], [], []
    for dur in TIMEFRAMES:
        buf = empty_buffer(num_symbols, window)
        p = px.copy()
        for b in range(window):
            rows, ts, vals, p = updates(t0 + b * dur, p, dur)
            buf = apply_updates(buf, rows, ts, vals)
        bufs.append(buf)
        carries.append(initial_regime_carry(num_symbols))
        pxs.append(p)
    jax.block_until_ready(bufs[-1].values)

    tracked = jnp.asarray(np.ones(num_symbols, dtype=bool))

    @jax.jit
    def step(bufs, carries, upds, timestamps):
        """Apply one (possibly empty) update batch per timeframe, then
        build all four contexts + the vectorized scorer."""
        outs, new_bufs, new_carries = [], [], []
        for buf, carry, upd, ts in zip(bufs, carries, upds, timestamps):
            buf = apply_updates(buf, *upd)
            fresh = fresh_mask(buf, ts)
            from binquant_tpu.engine.buffer import materialize

            # the context kernel consumes right-aligned windows; the ring
            # carries across ticks, the canonical view is per-tick
            context, carry = compute_market_context(
                materialize(buf), fresh, tracked, jnp.int32(0), ts, carry, cfg
            )
            ev = score_signal_candidate(
                context,
                is_short=jnp.asarray(False),
                local_score=jnp.ones((num_symbols,), jnp.float32),
                symbol_rs=context.features.relative_strength_vs_btc,
                symbol_trend=context.features.trend_score,
            )
            outs.append(
                jnp.stack(
                    [
                        context.long_regime_score,
                        context.market_stress_score,
                        jnp.mean(ev.adjusted_score),
                    ]
                )
            )
            new_bufs.append(buf)
            new_carries.append(carry)
        return jnp.stack(outs), new_bufs, new_carries

    def empty_upd():
        return (
            np.full(num_symbols, -1, np.int32),
            np.full(num_symbols, -1, np.int32),
            np.zeros((num_symbols, NUM_FIELDS), np.float32),
        )

    def fresh_upds(k: int):
        """One new bar per timeframe at bar index window+k."""
        upds, tss = [], []
        for j, dur in enumerate(TIMEFRAMES):
            ts_s = t0 + (window + k) * dur
            rows, ts, vals, pxs[j] = updates(ts_s, pxs[j], dur)
            upds.append((rows, ts, vals))
            tss.append(jnp.asarray(np.int32(ts_s)))
        return upds, tss

    ts_last = [
        jnp.asarray(np.int32(t0 + (window - 1) * dur)) for dur in TIMEFRAMES
    ]
    no_upd = [empty_upd() for _ in TIMEFRAMES]

    # warm both branches' compiles
    for k in range(max(warmup, 1)):
        out, bufs, carries = step(bufs, carries, no_upd, ts_last)
        upds, tss = fresh_upds(k)
        out, bufs, carries = step(bufs, carries, upds, tss)
        ts_last = tss
    jax.block_until_ready(out)
    # the context must actually be built (all symbols fresh at each ts)
    assert np.isfinite(np.asarray(out)).all()
    base = max(warmup, 1)

    # --- fresh-bar phase (headline): every tick appends a bar per
    # timeframe. Pipelined like the main bench: dispatch tick k, start its
    # result's async D2H, consume tick k-DEPTH's landed result — so the
    # steady-state measures the scoring step's device throughput, not the
    # host↔device round trip (~150 ms through the tunnel, ~0 local).
    from collections import deque

    # cap the pipeline depth well below the tick count: with depth >=
    # ticks no iteration ever blocks on a result and the "latencies" are
    # meaningless async-dispatch times (smoke mode runs 5 ticks)
    depth = max(1, min(depth, ticks // 2))
    fresh_lat = []
    pending: deque = deque()
    for k in range(ticks):
        upds, tss = fresh_upds(base + k)
        start = time.perf_counter()
        out, bufs, carries = step(bufs, carries, upds, tss)
        try:
            out.copy_to_host_async()
        except AttributeError:
            pass
        pending.append(out)
        if len(pending) > depth:
            np.asarray(pending.popleft())
        fresh_lat.append((time.perf_counter() - start) * 1000.0)
        ts_last = tss
    while pending:
        np.asarray(pending.popleft())

    # --- serial fresh-bar e2e: dispatch + same-tick fetch (full RTT)
    serial_lat = []
    for k in range(min(ticks, 24)):
        upds, tss = fresh_upds(base + ticks + k)
        start = time.perf_counter()
        out, bufs, carries = step(bufs, carries, upds, tss)
        np.asarray(out)
        serial_lat.append((time.perf_counter() - start) * 1000.0)
        ts_last = tss

    # --- refinement phase: re-evaluate the final timestamps, no new bars
    refine_lat = []
    for _ in range(min(ticks, 24)):
        start = time.perf_counter()
        out, bufs, carries = step(bufs, carries, no_upd, ts_last)
        np.asarray(out)
        refine_lat.append((time.perf_counter() - start) * 1000.0)

    fresh = np.array(fresh_lat)
    serial = np.array(serial_lat)
    refine = np.array(refine_lat)
    return {
        "p50_ms": float(np.percentile(fresh, 50)),
        "p99_ms": float(np.percentile(fresh, 99)),
        "serial_p50_ms": float(np.percentile(serial, 50)),
        "serial_p99_ms": float(np.percentile(serial, 99)),
        "refinement_p50_ms": float(np.percentile(refine, 50)),
        "refinement_p99_ms": float(np.percentile(refine, 99)),
        "scoring_evals_per_sec": float(
            num_symbols * len(TIMEFRAMES) / (fresh.mean() / 1000.0)
        ),
    }


def run_config1(ticks: int = 60) -> dict:
    """BASELINE config #1: the coinrule set on single-symbol BTCUSDT 15m
    klines down the per-symbol pandas path — the CPU reference
    configuration, timed through this repo's oracle (the reference-shaped
    engine the A/B harness trusts). Quantifies what ONE symbol costs on
    the legacy path; the batch bench amortizes ~2000 of these per tick."""
    import tempfile
    import time as _t

    from binquant_tpu.io.market_sim import MarketSimConfig, write_market_file
    from binquant_tpu.io.replay import load_klines_by_tick
    from binquant_tpu.oracle.evaluator import OracleEvaluator

    window = 200
    # enough session for the frames to reach the FULL window before the
    # timed tail starts: hours/4 buckets must cover window + ticks
    hours = (window + ticks + 20) // 4 + 1
    with tempfile.TemporaryDirectory() as td:
        path = f"{td}/config1.jsonl"
        # the canonical writer/loader pair — no second copy of the
        # 15m-from-5m aggregation
        write_market_file(
            path, MarketSimConfig(n_symbols=1, hours=hours, seed=5, n_pumps=0)
        )
        by_tick = load_klines_by_tick(path)

    ev = OracleEvaluator(
        window=window,
        required_fresh_symbols=1,
        min_coverage_ratio=0.0,
        enabled_strategies={
            "coinrule_price_tracker",
            "coinrule_twap_momentum_sniper",
            "coinrule_buy_low_sell_high",
            "coinrule_buy_the_dip",
        },
    )
    buckets = sorted(by_tick)
    assert len(buckets) >= window + ticks, "session too short to warm fully"
    lat: list[float] = []
    for n, bucket in enumerate(buckets):
        for k in sorted(by_tick[bucket], key=lambda k: k["open_time"]):
            ev.ingest(k)
        tick_ms = (bucket + 1) * 900 * 1000
        if n >= len(buckets) - ticks:  # frames hold `window` bars here
            w0 = _t.perf_counter()
            ev.evaluate(tick_ms)
            lat.append((_t.perf_counter() - w0) * 1000.0)
        else:
            ev.evaluate(tick_ms)
    a = np.array(lat)
    return {
        "p50_ms": float(np.percentile(a, 50)),
        "p99_ms": float(np.percentile(a, 99)),
        "symbol_ticks_per_sec": float(1000.0 / a.mean()),
        "ticks_timed": len(lat),
    }


def run_config2(num_symbols: int = 100, window: int = 400, iters: int = 50) -> dict:
    """BASELINE config #2: batched SMA/EMA/RSI over ~100 USDT pairs from a
    kline replay file — the core indicator batch on the device. Timing is
    amortized: ``iters`` async dispatches, one real D2H sync at the end
    (the serial device queue makes the final fetch wait for all of them).
    """
    import time as _t

    import jax

    from binquant_tpu.engine.buffer import (
        NUM_FIELDS,
        Field,
        apply_updates,
        empty_buffer,
    )
    from binquant_tpu.io.replay import load_klines_by_tick
    from binquant_tpu.ops.indicators import ema, rsi_wilder, sma

    fixture = "tests/fixtures/market_36h_100sym.jsonl.gz"
    by_tick = load_klines_by_tick(fixture)
    # replay the fixture's 5m stream into one (S, W) device buffer — ONE
    # batched apply_updates per 5m timestamp (three per bucket), the same
    # granularity the IngestBatcher produces, not one dispatch per kline
    buf = empty_buffer(num_symbols, window)
    rows: dict[str, int] = {}
    for bucket in sorted(by_tick):
        by_ts: dict[int, list[dict]] = {}
        for k in by_tick[bucket]:
            if (k["close_time"] - k["open_time"]) // 1000 in (299, 300):
                by_ts.setdefault(k["open_time"] // 1000, []).append(k)
        for ts_s in sorted(by_ts):
            batch = [
                k
                for k in by_ts[ts_s]
                if rows.setdefault(k["symbol"], len(rows)) < num_symbols
            ]
            if not batch:
                continue
            vals = np.zeros((len(batch), NUM_FIELDS), np.float32)
            for u, k in enumerate(batch):
                vals[u, Field.OPEN] = k["open"]
                vals[u, Field.HIGH] = k["high"]
                vals[u, Field.LOW] = k["low"]
                vals[u, Field.CLOSE] = k["close"]
                vals[u, Field.VOLUME] = k["volume"]
            buf = apply_updates(
                buf,
                np.array([rows[k["symbol"]] for k in batch], np.int32),
                np.full(len(batch), ts_s, np.int32),
                vals,
            )
    from binquant_tpu.engine.buffer import materialize

    close = materialize(buf).values[:, :, Field.CLOSE]
    np.asarray(close[:1, :1])  # land the replayed buffer

    @jax.jit
    def indicator_pass(c):
        return (
            sma(c, 7)[:, -1] + sma(c, 25)[:, -1] + sma(c, 100)[:, -1]
            + ema(c, 20)[:, -1] + rsi_wilder(c, 14)[:, -1]
        )

    np.asarray(indicator_pass(close))  # compile + sync
    t0 = _t.perf_counter()
    out = None
    for _ in range(iters):
        out = indicator_pass(close)
    np.asarray(out)
    per_pass_ms = (_t.perf_counter() - t0) / iters * 1000.0
    n_series = 5  # sma7/25/100, ema20, rsi14
    return {
        "pass_ms": per_pass_ms,
        "symbols": min(num_symbols, len(rows)),
        "window": window,
        "indicator_evals_per_sec": float(
            min(num_symbols, len(rows)) * n_series / (per_pass_ms / 1000.0)
        ),
    }


def _r3(value) -> float | None:
    """round(x, 3) that maps missing/NaN to JSON-safe None."""
    if value is None or value != value:
        return None
    return round(value, 3)


def _pallas_quantile_ab() -> dict | None:
    """Standalone pallas-vs-XLA A/B for the tail rolling quantile (the one
    pallas kernel). Publishes the measured story: at the production shape
    the XLA windowed sort wins BOTH standalone and embedded (the
    pallas_call boundary additionally blocks producer fusion), so XLA is
    the default and the kernel is the opt-in escape hatch for larger
    window/num_out shapes (ops/pallas_rolling.py pallas_available).
    Numbers include one tunnel round trip amortized over the iteration
    count — compare the two arms, not the absolutes. TPU only."""
    import jax

    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return None
    if not on_tpu:
        return None
    from binquant_tpu.ops.pallas_rolling import micro_bench

    S, W, window, num_out = 2048, 128, 80, 4
    try:
        r = micro_bench(S=S, W=W, window=window, num_out=num_out)
    except Exception as exc:
        # a broken kernel on a real TPU must be VISIBLE in the report,
        # not identical to "not a TPU run"
        return {"error": f"{type(exc).__name__}: {exc}"[:300]}
    return {
        "xla_ms_per_call": round(r["xla"], 3),
        "pallas_ms_per_call": round(r["pallas"], 3),
        "shape": f"{S}x{W} L={window} K={num_out} q=0.92",
        "default": "xla (standalone the two are within session noise; "
        "fused, the pallas_call boundary blocks producer fusion; kernel "
        "is opt-in via BQT_ENABLE_PALLAS)",
    }


def _device_preflight(
    timeouts: tuple[float, ...] = (120.0, 30.0, 30.0),
    backoffs: tuple[float, ...] = (8.0, 15.0),
) -> str | None:
    """Probe device availability in a SUBPROCESS with a hard timeout.

    The tunneled chip's availability is intermittent; when it is down,
    ``jax.devices()`` hangs the interpreter far past any useful budget
    (observed >10 min). A bench run that hangs produces no record at all —
    this probe converts an outage into one self-describing error line so
    the measurement history stays interpretable.

    Retries with backoff (VERDICT r5 weak #2) so a transient tunnel blip
    doesn't void a round's driver-captured perf evidence: only a SUSTAINED
    outage emits the error record. The FIRST attempt keeps a generous
    budget (a healthy cold tunnel can take minutes to init — the original
    single-probe allowance); the retries are short, for the blip case.
    Worst case ≈ sum(timeouts) + sum(backoffs) ≈ 3.5 min, still far under
    the hang it guards against. Returns None on the first healthy probe."""
    import subprocess
    import time as _time

    errors: list[str] = []
    for attempt, timeout_s in enumerate(timeouts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True,
                timeout=timeout_s,
                text=True,
            )
        except subprocess.TimeoutExpired:
            errors.append(
                f"attempt {attempt + 1}: probe timed out after {timeout_s:.0f}s"
            )
        else:
            if proc.returncode == 0:
                return None
            errors.append(
                f"attempt {attempt + 1}: backend init failed: "
                + proc.stderr.strip()[-200:]
            )
        if attempt < len(timeouts) - 1:
            _time.sleep(backoffs[min(attempt, len(backoffs) - 1)])
    window = sum(timeouts) + sum(backoffs[: len(timeouts) - 1])
    return (
        f"device backend unreachable after {len(timeouts)} probes over a "
        f"~{window:.0f}s window: " + "; ".join(errors)
    )


def main() -> int | None:
    # The bench quotes the UNTRACED hot path (the shape the p99<50ms
    # budget is judged against); an explicit BQT_TRACE_SAMPLE still wins,
    # so the tracing overhead itself can be measured by setting it to 1.
    os.environ.setdefault("BQT_TRACE_SAMPLE", "0")
    # Same rationale for the numeric digest: checked-in records quote the
    # digest-off wire (its own overhead is the device record's
    # numeric_digest.bytes_overhead_pct arm); set BQT_NUMERIC_DIGEST=1 to
    # measure a digest-on drive explicitly.
    os.environ.setdefault("BQT_NUMERIC_DIGEST", "0")
    os.environ.setdefault("BQT_DRIFT_METER", "0")
    # Ingest digest likewise: throughput arms quote the digest-off wire;
    # its own overhead is the device record's ingest_digest arm. Set
    # BQT_INGEST_DIGEST=1 to measure a digest-on drive explicitly.
    os.environ.setdefault("BQT_INGEST_DIGEST", "0")
    # Signal-outcome observatory likewise pinned OFF in throughput arms:
    # the benches quote the observatory-free hot path, and the outcome
    # bed's own cost is the dedicated --outcome-cost arm
    # (BENCH_OUTCOMES_CPU.json). Set BQT_OUTCOMES=1 to measure a
    # tracker-on drive explicitly.
    os.environ.setdefault("BQT_OUTCOMES", "0")
    # Subscription fan-out plane likewise pinned OFF in throughput arms
    # (the benches quote the plane-free hot path; its own cost is the
    # dedicated --fanout-throughput arm, BENCH_FANOUT_CPU.json). Set
    # BQT_FANOUT=1 to measure a plane-on drive explicitly.
    os.environ.setdefault("BQT_FANOUT", "0")
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true", help="tiny shapes")
    parser.add_argument(
        "--config1",
        action="store_true",
        help="BASELINE config #1: single-symbol coinrule set down the "
        "per-symbol pandas (reference-shaped) path",
    )
    parser.add_argument(
        "--config2",
        action="store_true",
        help="BASELINE config #2: batched SMA/EMA/RSI over 100 USDT pairs "
        "from the replay fixture",
    )
    parser.add_argument(
        "--config4",
        action="store_true",
        help="BASELINE config #4: context scoring over symbols x 4 timeframes",
    )
    parser.add_argument(
        "--sweep",
        action="store_true",
        help="scaling map: device step cost over S in {1024,2048,4096,8192}",
    )
    parser.add_argument(
        "--device",
        action="store_true",
        help="device-side cost breakdown only (stages, FLOPs, duty cycle)",
    )
    parser.add_argument(
        "--replay-throughput",
        action="store_true",
        help="replay/backtest throughput: serial per-tick drive vs fused "
        "scan chunks over an identical stream; writes BENCH_REPLAY_CPU.json"
        " when run on the CPU model (silicon runs print only)",
    )
    parser.add_argument(
        "--scan-chunk",
        type=int,
        default=64,
        help="ticks fused per scan dispatch in --replay-throughput",
    )
    parser.add_argument(
        "--ring-traffic",
        action="store_true",
        help="apply_updates-only scan traffic: cursor ring vs the retired "
        "shift-append (ISSUE 9 acceptance: >=5x fewer bytes/tick); merges "
        "into BENCH_REPLAY_CPU.json at the acceptance shape",
    )
    parser.add_argument(
        "--outcome-cost",
        action="store_true",
        help="signal-outcome maturation gather vs the wire step "
        "(ISSUE 12 acceptance: <5%% extra bytes at 2048x400); writes "
        "BENCH_OUTCOMES_CPU.json at the acceptance shape",
    )
    parser.add_argument(
        "--fanout-throughput",
        action="store_true",
        help="subscription match-kernel throughput (ISSUE 14): ONE "
        "dispatch joining --fanout-subs subscriptions against a fired "
        "tick, vs the extrapolated Python oracle, plus per-tick replay "
        "overhead vs BQT_FANOUT=0, plus the ISSUE-16 connection-scale "
        "sweep (10k->100k simulated consumers: shed rate + match->write "
        "p99 through the hub broadcast path); writes BENCH_FANOUT_CPU.json "
        "at >=1M subscriptions on the CPU model",
    )
    parser.add_argument(
        "--fanout-subs",
        type=int,
        default=1_000_000,
        help="population size for --fanout-throughput (smaller = "
        "print-only smoke)",
    )
    parser.add_argument(
        "--shard-throughput",
        action="store_true",
        help="virtual-device scaling of the sharded wire step (ISSUE 19): "
        "one subprocess per device count in {1,2,4,8}, identical drive, "
        "wall speedup at 4 shards vs 1 (>=1.6x acceptance, or a measured "
        "floor analysis when the host's core count floors the CPU model); "
        "writes BENCH_SHARD_CPU.json at 2048x400 on the CPU model",
    )
    parser.add_argument(
        "--shard-counts",
        type=str,
        default="1,2,4,8",
        help="comma list of device counts for --shard-throughput",
    )
    parser.add_argument(
        "--backtest-throughput",
        action="store_true",
        help="time-batched backtest backend vs the serial full-recompute "
        "drive (+ the vmapped 64-combo parameter-grid arm); writes "
        "BENCH_BACKTEST_CPU.json when run at the record shape on the CPU "
        "model (smoke shapes print only)",
    )
    parser.add_argument(
        "--backtest-chunk",
        type=int,
        default=12,
        help="ticks per time-batched dispatch in --backtest-throughput "
        "(the backend's memory knob)",
    )
    parser.add_argument(
        "--best-of",
        type=int,
        default=3,
        help="serialized repetitions per arm in --backtest-throughput; "
        "best run is quoted (the box carries neighbor noise)",
    )
    parser.add_argument("--symbols", type=int, default=2048)
    parser.add_argument("--window", type=int, default=400)
    parser.add_argument("--ticks", type=int, default=240)
    parser.add_argument("--warmup", type=int, default=10)
    parser.add_argument(
        "--depth",
        type=int,
        default=6,
        help="pipeline depth for the back-to-back phase (6 covers a "
        "tunneled-device RTT; a local chip needs the live default of 1)",
    )
    args = parser.parse_args()

    # Preflight only the modes that touch the device (config1 is the pure
    # pandas baseline and must stay runnable during outages), and only
    # when a hang is possible (a forced-CPU backend can't hang, so CI's
    # smoke job pays nothing).
    needs_device = not args.config1
    may_hang = os.environ.get("JAX_PLATFORMS", "").lower() != "cpu"
    if needs_device and may_hang:
        err = _device_preflight()
        if err is not None:
            metric = (
                "device_step_ms_at_2048" if args.sweep
                else "device_step_ms" if args.device
                else "replay_scanned_vs_serial_x" if args.replay_throughput
                else "indicator_batch_pass_ms" if args.config2
                else "context_scoring_4tf_p99_ms" if args.config4
                else "tick_p99_ms"
            )
            print(
                json.dumps(
                    {
                        "metric": metric,
                        "value": None,
                        "unit": "ms",
                        "vs_baseline": None,
                        "detail": {
                            "error": err,
                            "note": (
                                "no measurement this run — see "
                                "BENCH_SELF_r05.json for the last clean "
                                "self-measured run"
                            ),
                            "measurement_epoch": MEASUREMENT_EPOCH,
                        },
                    }
                )
            )
            return 1

    if args.smoke:
        args.symbols, args.window, args.ticks, args.warmup = 32, 120, 5, 2

    if args.backtest_throughput:
        import jax

        # documented zero-arg invocation measures the record shape; an
        # explicit --symbols/--window/--ticks makes a print-only smoke run
        record_shape = (
            args.symbols == parser.get_default("symbols")
            and args.window == parser.get_default("window")
            and args.ticks == parser.get_default("ticks")
        )
        if record_shape:
            symbols, window, ticks = 512, 240, 96
        else:
            symbols, window, ticks = args.symbols, args.window, max(args.ticks, 8)
        r = run_backtest_throughput(
            symbols,
            window,
            ticks=ticks,
            backtest_chunk=args.backtest_chunk,
            best_of=args.best_of,
        )
        record = {
            "metric": "backtest_vs_serial_full_x",
            "value": r["backtest_vs_serial_x"],
            "unit": "x",
            # acceptance: the backend must beat the serial full drive
            "vs_baseline": r["backtest_vs_serial_x"],
            "detail": r,
        }
        print(json.dumps(_stamped(record)))
        if jax.default_backend() == "cpu" and record_shape:
            with open("BENCH_BACKTEST_CPU.json", "w") as f:
                json.dump(record, f, indent=1)
        return

    if args.shard_throughput:
        import jax

        counts = tuple(
            int(c) for c in args.shard_counts.split(",") if c.strip()
        )
        if args.smoke:
            symbols, window, ticks, warmup = 64, 120, 6, 2
        else:
            symbols, window, ticks, warmup = (
                args.symbols,
                args.window,
                min(args.ticks, 24),
                min(args.warmup, 4),
            )
        r = run_shard_throughput(
            symbols, window, ticks=ticks, warmup=warmup, counts=counts
        )
        floored = r["cpu_model_floor"] is not None
        record = {
            "metric": "shard_wall_speedup_at_4_x",
            "value": r["wall_speedup_at_4_shards_x"],
            "unit": "x",
            # ISSUE 19 acceptance: >=1.6x wall at 4 shards — or the
            # measured floor analysis when the host cannot express it
            "vs_baseline": (
                round(r["wall_speedup_at_4_shards_x"] / 1.6, 3)
                if r["wall_speedup_at_4_shards_x"]
                else None
            ),
            "detail": r,
        }
        print(json.dumps(_stamped(record)))
        record_shape = (
            symbols == parser.get_default("symbols")
            and window == parser.get_default("window")
            and set(counts) >= {1, 2, 4, 8}
        )
        if jax.default_backend() == "cpu" and record_shape:
            with open("BENCH_SHARD_CPU.json", "w") as f:
                json.dump(record, f, indent=1)
            if floored:
                print(
                    "4-shard speedup floored by host core count — "
                    "cpu_model_floor analysis recorded",
                    file=sys.stderr,
                )
        return

    if args.fanout_throughput:
        import jax

        n_subs = 10_000 if args.smoke else args.fanout_subs
        r = run_fanout_throughput(n_subs=n_subs)
        # connection-scale arm (ISSUE 16 + the ISSUE 20 1M rung): the
        # hub's broadcast tier from 10k to 1M simulated consumers —
        # shed rate + match->write p99 per rung
        r["connection_sweep"] = run_fanout_connection_sweep(
            counts=(1_000, 2_000) if args.smoke
            else (10_000, 100_000, 1_000_000),
            frames=(8, 4) if args.smoke else (64, 32, 8),
        )
        # sustained-churn arm (ISSUE 20 tentpole): per-delta apply cost
        # must stay flat 10k -> 1M residents, zero bulk rebuilds
        r["churn_scale"] = run_fanout_churn_scale(
            sizes=(1_000, 10_000) if args.smoke
            else (10_000, 100_000, 1_000_000),
            bursts=6 if args.smoke else 24,
        )
        # snapshot-warm arm (ISSUE 20 tentpole b): restart-by-load vs
        # the full cold rebuild at the same population
        r["snapshot_warm"] = run_fanout_snapshot_warm(
            n_subs=10_000 if args.smoke else args.fanout_subs
        )
        record = {
            "metric": "fanout_match_sub_signals_per_s",
            "value": r["sub_signal_matches_per_s"],
            "unit": "sub*signal/s",
            # the what-it-replaces ratio: one device dispatch vs the
            # pure-Python subscription loop at the same population
            "vs_baseline": r["speedup_vs_python_oracle_x"],
            "detail": r,
        }
        print(json.dumps(_stamped(record)))
        if jax.default_backend() == "cpu" and n_subs >= 1_000_000:
            with open("BENCH_FANOUT_CPU.json", "w") as f:
                json.dump(record, f, indent=1)
        return

    if args.outcome_cost:
        import jax

        r = run_outcome_cost(args.symbols, args.window)
        record = {
            "metric": "outcome_gather_vs_wire_bytes_pct",
            "value": r["gather_vs_wire_bytes_pct"],
            "unit": "%",
            # ISSUE 12 acceptance: the maturation gather must stay under
            # 5% of the wire step's bytes (>1 = inside budget)
            "vs_baseline": (
                round(5.0 / r["gather_vs_wire_bytes_pct"], 3)
                if r["gather_vs_wire_bytes_pct"]
                else None
            ),
            "detail": r,
        }
        print(json.dumps(_stamped(record)))
        if (
            jax.default_backend() == "cpu"
            and args.symbols >= 2048
            and args.window >= 400
        ):
            with open("BENCH_OUTCOMES_CPU.json", "w") as f:
                json.dump(record, f, indent=1)
        return

    if args.ring_traffic:
        import jax

        r = run_ring_traffic(
            args.symbols, args.window, ticks=min(max(args.ticks, 8), 64)
        )
        record = {
            "metric": "ring_traffic_bytes_reduction_x",
            "value": r["bytes_reduction_x"],
            "unit": "x",
            # ISSUE 9 acceptance floor: >=5x fewer apply_updates-only
            # scan bytes/tick than the shift layout
            "vs_baseline": (
                round(r["bytes_reduction_x"] / 5.0, 3)
                if r["bytes_reduction_x"]
                else None
            ),
            "detail": r,
        }
        print(json.dumps(_stamped(record)))
        if (
            jax.default_backend() == "cpu"
            and args.symbols >= 2048
            and args.window >= 400
        ):
            # the tracked regression surface rides in the replay record;
            # an unreadable record means SKIP the merge (printing above
            # already reported the numbers) — rewriting would erase the
            # replay metric the file exists to track
            try:
                with open("BENCH_REPLAY_CPU.json") as f:
                    replay_record = json.load(f)
            except (OSError, ValueError):
                print(
                    "BENCH_REPLAY_CPU.json unreadable — ring_traffic not "
                    "merged (rerun bench.py --replay-throughput first)",
                    file=sys.stderr,
                )
                return
            replay_record.setdefault("detail", {})["ring_traffic"] = r
            with open("BENCH_REPLAY_CPU.json", "w") as f:
                json.dump(replay_record, f, indent=1)
        return

    if args.replay_throughput:
        import jax

        # the documented zero-arg invocation measures (and records) the
        # acceptance shape's >=256 ticks; an EXPLICIT --ticks still wins
        # (smoke runs pass small counts and are print-only below)
        ticks = (
            256 if args.ticks == parser.get_default("ticks")
            else max(args.ticks, 16)
        )
        r = run_replay_throughput(
            args.symbols,
            args.window,
            ticks=ticks,
            scan_chunk=args.scan_chunk,
        )
        if args.symbols >= 2048:
            # companion point in the dispatch-bound regime (refdiff-scale
            # shapes, where per-tick compute is small next to the Python+
            # dispatch overhead the scan erases) — the 2048x400 headline
            # sits on the CPU model's bandwidth floor instead (see
            # cpu_model_floor_note), so the record carries both
            r["dispatch_bound_point"] = run_replay_throughput(
                256, 120, ticks=ticks, scan_chunk=args.scan_chunk
            )
        record = {
            "metric": "replay_scanned_vs_serial_x",
            "value": r["scanned_vs_serial_x"],
            "unit": "x",
            # ISSUE 5 acceptance floor: >= 5x the serial drive
            "vs_baseline": (
                round(r["scanned_vs_serial_x"] / 5.0, 3)
                if r["scanned_vs_serial_x"]
                else None
            ),
            "detail": r,
        }
        print(json.dumps(_stamped(record)))
        # only the acceptance shape overwrites the checked-in record —
        # smoke-shape runs (make replay-smoke) print only
        if (
            jax.default_backend() == "cpu"
            and args.symbols >= 2048
            and args.window >= 400
            and ticks >= 256
        ):
            # carry the previously-merged ring_traffic section over — a
            # replay rerun must not erase the --ring-traffic acceptance
            # numbers that were merged into the same record
            try:
                with open("BENCH_REPLAY_CPU.json") as f:
                    prior = json.load(f).get("detail", {}).get("ring_traffic")
            except (OSError, ValueError):
                prior = None
            if prior is not None:
                record["detail"]["ring_traffic"] = prior
            with open("BENCH_REPLAY_CPU.json", "w") as f:
                json.dump(record, f, indent=1)
        return

    if args.sweep:
        sweep = run_sweep(window=args.window)
        ref_point = next(
            (p for p in sweep["points"] if p["symbols"] == 2048), sweep["points"][0]
        )
        print(
            json.dumps(_stamped(
                {
                    "metric": "device_step_ms_at_2048",
                    "value": ref_point["step_ms"],
                    "unit": "ms",
                    "vs_baseline": round(50.0 / ref_point["step_ms"], 3),
                    "detail": dict(sweep),
                })
            )
        )
        return

    if args.device:
        d = device_cost_breakdown(args.symbols, args.window, per_strategy=True)
        print(
            json.dumps(_stamped(
                {
                    "metric": "device_step_ms",
                    "value": d["step_ms"],
                    "unit": "ms",
                    "vs_baseline": round(50.0 / d["step_ms"], 3),
                    "detail": dict(d),
                })
            )
        )
        return

    if args.config1:
        stats = run_config1()
        value = round(stats["p99_ms"], 3)
        print(
            json.dumps(_stamped(
                {
                    "metric": "legacy_single_symbol_tick_p99_ms",
                    "value": value,
                    "unit": "ms",
                    # vs the batch path: the engine evaluates ~2000 symbols
                    # inside the SAME 50ms budget one legacy symbol burns
                    "vs_baseline": round(50.0 / value, 3) if value > 0 else 0.0,
                    "detail": {
                        **{k: round(v, 3) for k, v in stats.items()},
                        "measurement": (
                            "coinrule set, single BTCUSDT, per-symbol "
                            "pandas oracle (the reference-shaped path)"
                        ),
                    },
                })
            )
        )
        return

    if args.config2:
        stats = run_config2()
        value = round(stats["pass_ms"], 3)
        print(
            json.dumps(_stamped(
                {
                    "metric": "indicator_batch_pass_ms",
                    "value": value,
                    "unit": "ms",
                    "vs_baseline": round(50.0 / value, 3) if value > 0 else 0.0,
                    "detail": {
                        **{k: round(v, 3) for k, v in stats.items()},
                        "measurement": (
                            "SMA(7/25/100)+EMA(20)+RSI(14) one jit'd pass "
                            "over the replay fixture's 100 symbols, real "
                            "D2H sync, amortized over 50 passes"
                        ),
                    },
                })
            )
        )
        return

    if args.config4:
        stats = run_config4(
            args.symbols, args.window, args.ticks, args.warmup, args.depth
        )
        value = round(stats["p99_ms"], 3)
        print(
            json.dumps(_stamped(
                {
                    "metric": "context_scoring_4tf_p99_ms",
                    "value": value,
                    "unit": "ms",
                    "vs_baseline": round(50.0 / value, 3) if value > 0 else 0.0,
                    "detail": {
                        "symbols": args.symbols,
                        "window": args.window,
                        "timeframes": 4,
                        "measurement": (
                            "fresh-bar (append + context build) pipelined "
                            "steady-state headline; serial_* = blocking "
                            "dispatch+fetch per tick; refinement = same-ts "
                            "re-eval (serial)"
                        ),
                        "p50_ms": round(stats["p50_ms"], 3),
                        "serial_p50_ms": round(stats["serial_p50_ms"], 3),
                        "serial_p99_ms": round(stats["serial_p99_ms"], 3),
                        "refinement_p50_ms": round(stats["refinement_p50_ms"], 3),
                        "refinement_p99_ms": round(stats["refinement_p99_ms"], 3),
                        "scoring_evals_per_sec": round(
                            stats["scoring_evals_per_sec"]
                        ),
                    },
                })
            )
        )
        return

    stats = run(args.symbols, args.window, args.ticks, args.warmup, args.depth)
    # skipped under --smoke: the breakdown compiles ~6 extra XLA programs,
    # pure wall-clock for the CI sanity job which never asserts on it
    device = (
        None if args.smoke else device_cost_breakdown(args.symbols, args.window)
    )
    value = round(stats["p99_ms"], 3)
    print(
        json.dumps(_stamped(
            {
                "metric": "tick_p99_ms",
                "value": value,
                "unit": "ms",
                "vs_baseline": round(50.0 / value, 3) if value > 0 else 0.0,
                "detail": {
                    "symbols": args.symbols,
                    "window": args.window,
                    "p50_ms": round(stats["p50_ms"], 3),
                    "mean_ms": round(stats["mean_ms"], 3),
                    "throughput_p50_ms": round(stats["throughput_p50_ms"], 3),
                    "throughput_p99_ms": round(stats["throughput_p99_ms"], 3),
                    "throughput_depth": args.depth,
                    "e2e_p50_ms": round(stats["e2e_p50_ms"], 3),
                    "e2e_p99_ms": round(stats["e2e_p99_ms"], 3),
                    "device_dispatch_p99_ms": round(
                        stats["device_dispatch_p99_ms"], 3
                    ),
                    "wire_fetch_p99_ms": round(stats["wire_fetch_p99_ms"], 3),
                    "signal_lag_p50_ms": _r3(stats["signal_lag_p50_ms"]),
                    "signal_lag_p99_ms": _r3(stats["signal_lag_p99_ms"]),
                    "candle_to_emit_p50_ms": _r3(
                        stats["candle_to_emit_p50_ms"]
                    ),
                    "candle_to_emit_p99_ms": _r3(
                        stats["candle_to_emit_p99_ms"]
                    ),
                    "classic_lag_p99_ms": _r3(stats["classic_lag_p99_ms"]),
                    "serial_lag_p99_ms": _r3(stats["serial_lag_p99_ms"]),
                    "rtt_probe_ms": round(stats["rtt_probe_ms"], 3),
                    "rtt_probe_max_ms": round(stats["rtt_probe_max_ms"], 3),
                    "serial_projection_p50_ms": round(
                        stats["serial_projection_p50_ms"], 3
                    ),
                    "serial_projection_p99_ms": round(
                        stats["serial_projection_p99_ms"], 3
                    ),
                    "ticks_per_sec": round(stats["ticks_per_sec"], 1),
                    "pallas_quantile_ab": _pallas_quantile_ab(),
                    "measurement": (
                        "production SignalEngine.process_tick via its own "
                        "LatencyTracker. Headline: depth-1 at the 1 s live "
                        "cadence (main.py's shape — BASELINE north star). "
                        "throughput_*: back-to-back pipelined (no idle gap); "
                        "e2e: serial depth-0, full round trip per tick. "
                        "signal_lag/candle_to_emit: dispatch→emission and "
                        "candle-close→emission wall time with the fired-tick "
                        "fast path (consume_loop's emit_ready) — the true "
                        "signal latency; classic_lag: without the fast path "
                        "(one full cadence). rtt_probe_ms: device-link round "
                        "trip (tunnel tax ~150 ms here, ~0.1 ms on a local "
                        "chip) — subtract from serial/e2e and signal-lag "
                        "numbers to project an untunneled v5e-1."
                    ),
                    "symbol_strategy_evals_per_sec": round(
                        stats["symbol_evals_per_sec"]
                    ),
                    "evals_basis_strategies": stats["evals_basis_strategies"],
                    "stage_p99_ms": stats["stage_p99_ms"],
                    "device": device,
                    "measurement_epoch": MEASUREMENT_EPOCH,
                },
            })
        )
    )


if __name__ == "__main__":
    sys.exit(main())
