"""Market-regime subsystem, batched and jit-compiled.

TPU-native re-design of ``/root/reference/market_regime/``: instead of a
Python loop over fresh symbols building pydantic objects per candle, the
whole market context — per-symbol features, masked aggregates, stress and
tailwind scores, macro+micro regime ladders, and transition events vs the
carried previous state — is computed for all S symbols in one compiled
function. Categorical regimes live as int32 codes on device
(``binquant_tpu.enums``); the host edge materializes pydantic
``LiveMarketContext`` objects only for symbols that actually emit.
"""

from binquant_tpu.regime.context import (  # noqa: F401
    ContextConfig,
    MarketContext,
    RegimeCarry,
    SymbolFeatureArrays,
    compute_market_context,
    compute_symbol_features,
    initial_regime_carry,
)
from binquant_tpu.regime.grid_policy import GridOnlyPolicy  # noqa: F401
from binquant_tpu.regime.routing import (  # noqa: F401
    DEFAULT_REGIME_STABILITY_S,
    allows_long_autotrade_mask,
    is_regime_stable,
    long_autotrade_decision,
    regime_age_s,
)
from binquant_tpu.regime.scoring import (  # noqa: F401
    ContextScoreArrays,
    ScorerWeights,
    SignalEvaluation,
    adjust_score,
    evaluate_context_score,
    score_signal_candidate,
)
from binquant_tpu.regime.time_filter import (  # noqa: F401
    build_quiet_hours_signal_msg,
    is_autotrade_suppressed,
    is_quiet_hours,
)
