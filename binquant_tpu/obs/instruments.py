"""The metric catalogue: every family this codebase emits, defined once.

Instrumented modules import their instruments from here instead of
declaring families ad hoc — so (a) name/type/label collisions are
impossible, (b) importing ANY instrumented module registers the whole
catalogue and ``/metrics`` always exposes every family name, and (c) this
file + README.md §Observability are the same list in two forms. Keep the
two in sync when adding a family.

Label cardinality is deliberately bounded: stage names, strategy names,
exchange ids, gate names and outcome enums are all small fixed sets —
never put symbols, paths, or error strings in a label (those belong in the
event log).
"""

from __future__ import annotations

from binquant_tpu.obs.registry import REGISTRY

# -- tick pipeline (io/pipeline.py) -----------------------------------------

TICKS = REGISTRY.counter(
    "bqt_ticks_total", "Engine ticks processed (one batched device step each)."
)
SIGNALS = REGISTRY.counter(
    "bqt_signals_total",
    "Signals emitted through the sinks, after per-bar dedupe.",
    labels=("strategy",),
)
OVERFLOW_TICKS = REGISTRY.counter(
    "bqt_wire_overflow_ticks_total",
    "Ticks whose fired set overflowed the wire's compaction slots "
    "(full-summary fallback ran).",
)
QUEUE_DEPTH = REGISTRY.gauge(
    "bqt_queue_depth",
    "Ingest backlog: asyncio queue (consume loop) and per-interval "
    "batcher pending-candle counts at tick dispatch.",
    labels=("queue",),
)
STAGE_LATENCY = REGISTRY.histogram(
    "bqt_stage_latency_ms",
    "Per-stage pipeline latency in milliseconds (absorbs LatencyTracker; "
    "tick_total is the p99<50ms budget stage).",
    labels=("stage",),
)
HEARTBEAT_FAILURES = REGISTRY.counter(
    "bqt_heartbeat_write_failures_total",
    "Failed heartbeat-file writes (persistent failure degrades /healthz).",
)
SLOW_TICKS = REGISTRY.counter(
    "bqt_slow_ticks_total",
    "Traced ticks whose busy time breached BQT_TRACE_SLOW_MS (or that "
    "errored), attributed to the dominant top-level stage; the flight "
    "recorder force-emits each one's span tree + engine snapshot.",
    labels=("stage",),
)

# -- latency observatory (obs/latency.py, ISSUE 11) ---------------------------

FRESHNESS = REGISTRY.histogram(
    "bqt_freshness_ms",
    "End-to-end signal freshness per stage: close_to_dispatch / "
    "ingest_to_dispatch / dispatch_to_fetch / close_to_emit / "
    "close_to_sink_ack. close_to_* stages are logical (measured against "
    "the tick's own clock — exact live, deterministic in replay); the "
    "rest are real monotonic deltas.",
    labels=("stage",),
    buckets=(1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
             2500.0, 10000.0, 60000.0),
)
SINK_DELIVERY = REGISTRY.histogram(
    "bqt_sink_delivery_ms",
    "Per-sink delivery latency: candle close to the sink call returning "
    "(telegram measures the paced-queue enqueue ack, not wire delivery).",
    labels=("sink",),
    buckets=(1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
             2500.0, 10000.0, 60000.0),
)
FRESHNESS_SLO_BREACHES = REGISTRY.counter(
    "bqt_freshness_slo_breaches_total",
    "Signals whose worst close→sink-ack exceeded BQT_FRESHNESS_SLO_MS "
    "(each force-emits a freshness_slo_breach event with the producing "
    "chunk's host-phase breakdown).",
)
HOST_PHASE = REGISTRY.histogram(
    "bqt_host_phase_ms",
    "Host-phase dwell per drive (serial / scanned / backtest) and phase "
    "(plan / stack / dispatch / device_wait / decode / emit) — one "
    "observation per tick on the serial drive, per chunk-level bracket "
    "on the batch drives.",
    labels=("drive", "phase"),
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
             1000.0, 5000.0),
)
CHUNK_OCCUPANCY = REGISTRY.gauge(
    "bqt_chunk_occupancy_ratio",
    "The newest chunk's wall-clock split per drive: device_wait (blocking "
    "wire fetch — a lower bound on device busy), host (named host "
    "phases), dead_gap (unattributed residual), each as a fraction of "
    "chunk wall.",
    labels=("drive", "component"),
)

# -- signal-outcome observatory (obs/outcomes.py, ISSUE 12) -------------------

SIGNAL_FWD_RETURN = REGISTRY.histogram(
    "bqt_signal_forward_return",
    "Direction-signed forward return of an emitted signal at a fixed "
    "horizon (5m bars past the entry anchor), computed device-side from "
    "the live ring at maturation. Positive = the signal's direction won.",
    labels=("strategy", "horizon"),
    buckets=(-0.1, -0.05, -0.02, -0.01, -0.005, -0.002, 0.0,
             0.002, 0.005, 0.01, 0.02, 0.05, 0.1),
)
SIGNAL_MAE = REGISTRY.histogram(
    "bqt_signal_mae",
    "Max adverse excursion within the horizon, in direction-signed "
    "return space (always <= 0; LONG reads the window's lowest low, "
    "SHORT the highest high).",
    labels=("strategy", "horizon"),
    buckets=(-0.2, -0.1, -0.05, -0.02, -0.01, -0.005, -0.002, -0.001, 0.0),
)
SIGNAL_MFE = REGISTRY.histogram(
    "bqt_signal_mfe",
    "Max favorable excursion within the horizon, in direction-signed "
    "return space (always >= 0).",
    labels=("strategy", "horizon"),
    buckets=(0.0, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2),
)
SIGNAL_HIT_RATE = REGISTRY.gauge(
    "bqt_signal_hit_rate",
    "Fraction of matured signals per (strategy, horizon) whose "
    "direction-signed forward return was positive.",
    labels=("strategy", "horizon"),
)
OUTCOME_OPEN = REGISTRY.gauge(
    "bqt_signal_outcomes_open",
    "Open-signal registry occupancy: emitted signals with at least one "
    "horizon still maturing.",
)
OUTCOME_MATURED = REGISTRY.counter(
    "bqt_signal_outcomes_matured_total",
    "Matured (signal, horizon) outcome pairs per strategy and horizon.",
    labels=("strategy", "horizon"),
)
OUTCOME_EVICTIONS = REGISTRY.counter(
    "bqt_signal_outcome_evictions_total",
    "Open signals evicted unmatured because the registry hit "
    "BQT_OUTCOME_CAP (oldest-first).",
)
OUTCOME_TRUNCATED = REGISTRY.counter(
    "bqt_signal_outcomes_truncated_total",
    "Matured pairs excluded from the scoreboard because the ring no "
    "longer held the full horizon window (W too small for the horizon + "
    "chunk retention bound) or the row's history vanished (churn).",
)

# -- event log (obs/events.py) ----------------------------------------------

EVENTLOG_DROPPED = REGISTRY.counter(
    "bqt_eventlog_dropped_total",
    "Event-log records dropped: the sink write failed, or emit was "
    "called after close().",
)

# -- device step (engine/step.py) -------------------------------------------

FULL_RECOMPUTE = REGISTRY.counter(
    "bqt_full_recompute_total",
    "Ticks routed to the full-window recompute while the incremental "
    "fast path is enabled, by reason (cold_start / rewrite / backfill / "
    "churn / audit). Full ticks re-anchor the carried indicator state.",
    labels=("reason",),
)
SYMBOLS_PER_TICK = REGISTRY.gauge(
    "bqt_symbols_per_tick",
    "Symbols with fresh candles applied in the last dispatched tick.",
    labels=("interval",),
)
JIT_RECOMPILES = REGISTRY.counter(
    "bqt_jit_recompiles_total",
    "New (shape, wire-key, config) dispatch signatures — each one is a "
    "jax trace+compile of the tick step.",
    labels=("fn",),
)
BC_DIRTY_ROWS = REGISTRY.gauge(
    "bqt_bc_dirty_rows",
    "Beta/corr carry rows marked dirty on the last incremental tick "
    "(asymmetric append vs the BTC row; they decode as null until the "
    "next full-recompute resync) — sustained non-zero means resync "
    "pressure.",
)
SCANNED_TICKS = REGISTRY.counter(
    "bqt_scanned_ticks_total",
    "Replayed ticks evaluated inside fused lax.scan chunks (replay, "
    "catch-up, backtesting lanes) instead of one dispatch each.",
)
SCAN_CHUNKS = REGISTRY.counter(
    "bqt_scan_chunks_total",
    "Fused scan-chunk dispatches (each replaces chunk-length per-tick "
    "dispatches).",
)
SCAN_OVERFLOW_RERUNS = REGISTRY.counter(
    "bqt_scan_overflow_reruns_total",
    "Scan chunks re-driven through the serial per-tick path because a "
    "tick's fired set overflowed the wire's compaction slots.",
)

# -- time-batched backtest backend (binquant_tpu/backtest) --------------------

BACKTEST_TICKS = REGISTRY.counter(
    "bqt_backtest_ticks_total",
    "Replayed ticks evaluated inside time-batched backtest chunks "
    "(full-recompute (S, W+T) kernel) instead of one dispatch each.",
)
BACKTEST_CHUNKS = REGISTRY.counter(
    "bqt_backtest_chunks_total",
    "Time-batched backtest chunk dispatches.",
)
BACKTEST_OVERFLOW_RERUNS = REGISTRY.counter(
    "bqt_backtest_overflow_reruns_total",
    "Backtest chunks re-driven through the serial per-tick path because "
    "a tick's fired set overflowed the wire's compaction slots.",
)

# -- numeric-health observatory (ISSUE 7) -------------------------------------

NUMERIC_NONFINITE = REGISTRY.gauge(
    "bqt_numeric_nonfinite_rows",
    "Rows with NaN/Inf leakage on the last digest-carrying tick, per "
    "pipeline stage (features5 / features15 / indicators / strategies), "
    "counted only among rows whose data-sufficiency gates promise finite "
    "values — warm-up NaN is excluded by construction.",
    labels=("stage", "kind"),
)
NUMERIC_ANOMALIES = REGISTRY.counter(
    "bqt_numeric_anomaly_ticks_total",
    "Ticks whose digest NaN/Inf leakage exceeded BQT_NUMERIC_NAN_BUDGET "
    "(each force-emits a numeric_anomaly event with the decoded digest "
    "and an engine snapshot).",
)
NUMERIC_ABSMAX = REGISTRY.gauge(
    "bqt_numeric_absmax",
    "Absolute-max of key device intermediates on the last digest-carrying "
    "tick (close5 / close15 / volume5 / volume15 / score) — a runaway "
    "series shows here before it NaNs.",
    labels=("series",),
)
FIRED_PER_TICK = REGISTRY.histogram(
    "bqt_fired_rows_per_tick",
    "Device-side per-strategy trigger counts per digest-carrying tick "
    "(pre-dedupe, pre-enablement-filter) — the fired-breadth histogram.",
    labels=("strategy",),
    buckets=(0.0, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0),
)
CARRY_DRIFT = REGISTRY.histogram(
    "bqt_carry_drift",
    "Max-abs drift between the carried indicator state and the fresh "
    "full-recompute values, measured per family at every audit tick "
    "BEFORE the resync overwrites the carry (BQT_CARRY_AUDIT_EVERY).",
    labels=("family",),
    buckets=(1e-7, 1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0, 100.0),
)
CARRY_DRIFT_ULP = REGISTRY.gauge(
    "bqt_carry_drift_ulp",
    "Max f32-ULP distance of the last audit tick's carried-vs-fresh "
    "comparison, per family.",
    labels=("family",),
)
CARRY_DRIFT_ALARMS = REGISTRY.counter(
    "bqt_carry_drift_alarms_total",
    "Audit ticks where a family's scale-normalized drift (each leaf's "
    "max-abs over that leaf's magnitude scale, maxed across the family's "
    "leaves) exceeded BQT_DRIFT_TOL — each force-emits a "
    "carry_drift_alarm event.",
    labels=("family",),
)

# -- executable/compile ledger (obs/ledger.py) --------------------------------

COMPILE_SECONDS = REGISTRY.counter(
    "bqt_compile_seconds",
    "Wall seconds spent compiling each engine-owned jit executable "
    "(first launch per dispatch signature; persistent-cache hits "
    "deserialize in ~100ms and still count here).",
    labels=("executable",),
)
EXECUTABLE_BYTES = REGISTRY.gauge(
    "bqt_executable_bytes",
    "XLA cost_analysis bytes-accessed of the newest recorded signature "
    "per executable (the per-dispatch memory-traffic bill).",
    labels=("executable",),
)
EXECUTABLE_FLOPS = REGISTRY.gauge(
    "bqt_executable_flops",
    "XLA cost_analysis flops of the newest recorded signature per "
    "executable.",
    labels=("executable",),
)

# -- ingest buffers + registry (engine/buffer.py) ---------------------------

INGEST_DEDUP_OVERWRITES = REGISTRY.counter(
    "bqt_ingest_dedup_overwrites_total",
    "Pending candles overwritten before drain by a re-sent (symbol, "
    "open_time) — the keep-last dedupe evicting the stale payload.",
)
REGISTRY_SYMBOLS = REGISTRY.gauge(
    "bqt_registry_symbols",
    "Occupied symbol rows in the device ring buffer registry.",
)
REGISTRY_CAPACITY_ERRORS = REGISTRY.counter(
    "bqt_registry_capacity_errors_total",
    "Symbol-add attempts refused because the registry overflowed "
    "BQT_MAX_SYMBOLS.",
)

# -- websocket ingest (io/websocket.py) -------------------------------------

WS_FRAMES = REGISTRY.counter(
    "bqt_ws_frames_total",
    "Raw websocket frames received, per exchange (all message kinds).",
    labels=("exchange",),
)
WS_RECONNECTS = REGISTRY.counter(
    "bqt_ws_reconnects_total",
    "Websocket client drops that entered the reconnect-backoff loop.",
    labels=("exchange",),
)
WS_PARSE_ERRORS = REGISTRY.counter(
    "bqt_ws_parse_errors_total",
    "Websocket frames that failed JSON/shape parsing, per exchange — a "
    "poisoned feed shows here (plus rate-limited ws_bad_frame events), "
    "not just in the error log.",
    labels=("exchange",),
)

# -- emission sinks (io/emission.py, io/telegram.py, io/autotrade.py) -------

SINK_EMISSIONS = REGISTRY.counter(
    "bqt_sink_emissions_total",
    "Per-sink emission outcomes (ok / error / retry / suppressed / "
    "attempt / refused / launched / grid_deployed).",
    labels=("sink", "outcome"),
)
AUTOTRADE_REFUSALS = REGISTRY.counter(
    "bqt_autotrade_refusals_total",
    "Autotrade admissions refused, by gate name.",
    labels=("gate",),
)

# -- durable delivery plane (io/delivery.py, ISSUE 13) ------------------------

DELIVERY_ENQUEUED = REGISTRY.counter(
    "bqt_delivery_enqueued_total",
    "Signals accepted by the delivery plane per sink (finalize enqueues "
    "and returns; the WAL put for at-least-once sinks precedes this).",
    labels=("sink",),
)
DELIVERY_ACKED = REGISTRY.counter(
    "bqt_delivery_acked_total",
    "Deliveries the sink confirmed, per sink (at-least-once sinks also "
    "write the WAL ack record here).",
    labels=("sink",),
)
DELIVERY_RETRIES = REGISTRY.counter(
    "bqt_delivery_retries_total",
    "Failed delivery attempts per sink (each schedules a jittered "
    "exponential-backoff retry, or a shed once a lossy sink's attempt "
    "budget is spent).",
    labels=("sink",),
)
DELIVERY_SHED = REGISTRY.counter(
    "bqt_delivery_shed_total",
    "Lossy-class signals dropped by the plane, by reason (queue_full / "
    "breaker_open / retries_exhausted / encode_error). At-least-once "
    "sinks never appear here except queue_full with durability disabled.",
    labels=("sink", "reason"),
)
DELIVERY_BREAKER = REGISTRY.counter(
    "bqt_delivery_breaker_transitions_total",
    "Circuit-breaker state transitions per sink (open / half_open / "
    "closed); each also emits a delivery_breaker event.",
    labels=("sink", "state"),
)
DELIVERY_QUEUE = REGISTRY.gauge(
    "bqt_delivery_queue_depth",
    "Outbox queue occupancy per sink (bounded by BQT_DELIVERY_QUEUE).",
    labels=("sink",),
)
DELIVERY_WAL_UNACKED = REGISTRY.gauge(
    "bqt_delivery_wal_unacked",
    "Write-ahead-log puts without an ack yet, per at-least-once sink — "
    "sustained growth means the sink is down and the outbox is absorbing.",
    labels=("sink",),
)
DELIVERY_WAL_REPLAYED = REGISTRY.counter(
    "bqt_delivery_wal_replayed_total",
    "Unacked WAL entries re-enqueued at boot (the previous process was "
    "killed between accept and sink ack) — the at-least-once replay path.",
    labels=("sink",),
)

# -- binbot REST client (io/binbot.py) --------------------------------------

BINBOT_REQUESTS = REGISTRY.counter(
    "bqt_binbot_requests_total",
    "Binbot backend REST calls by method and outcome "
    "(ok / http_error / backend_error / transport_error).",
    labels=("method", "outcome"),
)
BINBOT_RETRIES = REGISTRY.counter(
    "bqt_binbot_retries_total",
    "Binbot REST retry outcomes: retry (a capped, jittered in-client "
    "retry ran after a transport error / 5xx) and exhausted (the retry "
    "budget was spent; the error surfaced to the caller and a "
    "binbot_retry_exhausted event recorded it).",
    labels=("outcome",),
)

# -- checkpointing (io/checkpoint.py) ---------------------------------------

CHECKPOINT_SAVES = REGISTRY.counter(
    "bqt_checkpoint_saves_total",
    "Engine-state snapshot attempts by outcome (ok / error).",
    labels=("outcome",),
)

# -- subscription fan-out plane (binquant_tpu/fanout, ISSUE 14) -------------

FANOUT_SUBSCRIPTIONS = REGISTRY.gauge(
    "bqt_fanout_subscriptions",
    "Live subscriptions compiled into the device bitset planes "
    "(user x symbols/strategies/regimes/min-strength).",
)
FANOUT_RECOMPILES = REGISTRY.counter(
    "bqt_fanout_recompiles_total",
    "Device plane resyncs by kind: incremental (dirty word columns "
    "scattered in one jit'd update after churn) vs full (first use, "
    "capacity growth, or a symbol-row refresh after registry churn — "
    "the only case that retraces the match kernel; the tick step never "
    "retraces either way).",
    labels=("kind",),
)
FANOUT_MATCH_DISPATCHES = REGISTRY.counter(
    "bqt_fanout_match_dispatches_total",
    "Per-tick subscription match kernel launches (one per fired tick, "
    "joining every deduped fired slot in a single dispatch).",
)
FANOUT_RECIPIENTS = REGISTRY.counter(
    "bqt_fanout_matched_recipients_total",
    "Total (signal, subscriber) matches the kernel produced.",
)
FANOUT_PUBLISHED = REGISTRY.counter(
    "bqt_fanout_published_total",
    "Signal frames entering the broadcast tier (outbox-appended; "
    "delivered to connections by the hub or the delivery worker).",
)
FANOUT_FRAMES = REGISTRY.counter(
    "bqt_fanout_frames_total",
    "Frames written to subscriber connections, per transport.",
    labels=("transport",),
)
FANOUT_CONNECTIONS = REGISTRY.gauge(
    "bqt_fanout_connections",
    "Open hub connections per transport (ws / sse).",
    labels=("transport",),
)
FANOUT_SHED = REGISTRY.counter(
    "bqt_fanout_shed_total",
    "Broadcast frames dropped by reason (slow_consumer: a connection's "
    "bounded queue was full; resume_overflow: a reconnect gap exceeded "
    "the queue) — counted, never silent; the client recovers by "
    "reconnecting with its cursor.",
    labels=("reason",),
)
FANOUT_RESUME_REPLAYED = REGISTRY.counter(
    "bqt_fanout_resume_replayed_total",
    "Frames replayed from the broadcast outbox to reconnecting clients "
    "presenting a cursor.",
)
FANOUT_RESUME_FALLBACK = REGISTRY.counter(
    "bqt_fanout_resume_fallback_total",
    "Cursor reconnects that could NOT be served from the hub's in-memory "
    "tail ring and fell back to a full outbox scan, by reason "
    "(tail_off: ring disabled; tail_cold: nothing broadcast yet this "
    "boot / ring invalidated by compaction; cursor_gap: cursor older "
    "than the retained ring; trace_cursor: provenance cursors resolve "
    "through the outbox).",
    labels=("reason",),
)
FANOUT_DELTA_WORDS = REGISTRY.histogram(
    "bqt_fanout_delta_words",
    "Words patched per incremental apply_subscription_deltas dispatch — "
    "the per-tick device cost of subscription churn (O(cells touched), "
    "independent of the resident population).",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
             1024.0, 4096.0),
)
FANOUT_COMPACTIONS = REGISTRY.counter(
    "bqt_fanout_compactions_total",
    "Tombstone-folding plane compactions (fragmentation crossed the "
    "fanout_compact_frac threshold): live slots re-packed dense, "
    "capacity shrunk toward the initial allocation, one counted FULL "
    "device resync.",
)
FANOUT_SNAPSHOT = REGISTRY.counter(
    "bqt_fanout_snapshot_total",
    "Fan-out snapshot sidecar operations by op (save / restore) and "
    "outcome (ok / rejected / error): the restart-warm boot path — "
    "rejected restores (torn save, version or plane-shape mismatch) "
    "fall back to a cold rebuild.",
    labels=("op", "outcome"),
)

# -- ingest-health observatory (ISSUE 15) -------------------------------------

INGEST_TRACKED = REGISTRY.gauge(
    "bqt_ingest_tracked_rows",
    "Tracked registry rows on the last ingest-digest tick (the universe "
    "the staleness/coverage counts below are judged over).",
)
INGEST_STALE = REGISTRY.gauge(
    "bqt_ingest_stale_rows",
    "Tracked rows with data whose newest bar's age exceeds the bucket "
    "threshold (1x / 3x / 10x the bar interval; cumulative thresholds — "
    "a row counted under 10x also counts under 1x), per interval, on the "
    "last digest tick. Sustained non-zero means per-symbol feed death.",
    labels=("interval", "bucket"),
)
INGEST_COVERAGE = REGISTRY.gauge(
    "bqt_ingest_coverage_rows",
    "Coverage funnel per interval on the last digest tick: covered "
    "(tracked rows holding any data) -> min_bars (filled >= MIN_BARS, "
    "strategy-sufficient) -> fresh (sufficient AND holding the evaluated "
    "bucket's bar).",
    labels=("interval", "stage"),
)
INGEST_MAX_AGE = REGISTRY.gauge(
    "bqt_ingest_max_age_seconds",
    "Age of the stalest tracked row's newest bar per interval on the "
    "last digest tick (0 when every covered row is fresh).",
    labels=("interval",),
)
INGEST_APPLIED = REGISTRY.counter(
    "bqt_ingest_applied_total",
    "Update-batch routing decoded from the per-tick ingest digest, per "
    "interval and kind (append / rewrite / gap_append / dropped) — "
    "device-classified with apply_updates' exact rules, summed over "
    "every sub-batch each finalized tick applied.",
    labels=("interval", "kind"),
)
INGEST_FEED_LAG = REGISTRY.histogram(
    "bqt_ingest_feed_lag_ms",
    "Exchange feed lag per candle at ingest: host wall-clock arrival "
    "minus the candle's close_time, per exchange. Replay lanes carry "
    "historical close times, so their readings saturate the top bucket "
    "by design.",
    labels=("exchange",),
    buckets=(50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 15000.0,
             60000.0, 300000.0),
)
INGEST_ANOMALIES = REGISTRY.counter(
    "bqt_ingest_anomaly_ticks_total",
    "Digest ticks whose 1x-stale row total exceeded "
    "BQT_INGEST_STALE_BUDGET (each force-emits an ingest_anomaly event "
    "with the decoded digest, the worst symbols, and an engine snapshot).",
)
INGEST_CHURN = REGISTRY.counter(
    "bqt_ingest_churn_total",
    "Symbol churn observed by the ingest monitor: a known symbol's "
    "registry row moved (listing churn re-homing) or the engine marked "
    "a churn carry-desync.",
)
INGEST_OOO = REGISTRY.counter(
    "bqt_ingest_out_of_order_total",
    "Host-classified non-append deliveries per interval (a candle at or "
    "behind the row's latest applied bar: same-bar rewrites and "
    "mid-history corrections/drops).",
    labels=("interval",),
)

# -- delivery-plane observatory + unified SLO plane (ISSUE 16) ---------------

DELIVERY_LAG = REGISTRY.histogram(
    "bqt_delivery_lag_ms",
    "End-to-end delivery lag per sink: candle close to the sink's FINAL "
    "successful ack (queue dwell + every retry/backoff included; "
    "WAL-replayed entries carry their original close anchor across the "
    "process kill). bqt_sink_delivery_ms predates the plane and keeps "
    "its freshness-gated semantics; this family is the ack-side truth.",
    labels=("sink",),
    buckets=(1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
             2500.0, 10000.0, 60000.0),
)
DELIVERY_BREAKER_STATE = REGISTRY.gauge(
    "bqt_delivery_breaker_state",
    "Current circuit-breaker state per sink (0=closed, 1=half_open, "
    "2=open) — the level companion to the "
    "bqt_delivery_breaker_transitions_total edge counter.",
    labels=("sink",),
)
DELIVERY_OLDEST_AGE = REGISTRY.gauge(
    "bqt_delivery_oldest_unacked_ms",
    "Age of the oldest unacked WAL record per at-least-once sink (wall "
    "clock since its put) — the outbox watermark: sustained growth means "
    "the head of the backlog is not moving.",
    labels=("sink",),
)
DELIVERY_CURSOR_LAG = REGISTRY.gauge(
    "bqt_delivery_cursor_lag",
    "Records behind head per consumer group: the three sink workers "
    "(queued + inflight + WAL-deferred entries not yet acked) and the "
    "fan-out hub as a fourth group (broadcast frames the laggiest open "
    "connection has not received).",
    labels=("group",),
)
FANOUT_CONN_QUEUE_DEPTH = REGISTRY.histogram(
    "bqt_fanout_conn_queue_depth",
    "Per-connection frame-queue occupancy sampled at every broadcast "
    "offer — the distribution (not just the max) of how far behind the "
    "hub's consumers run.",
    buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0),
)
FANOUT_WRITE_LATENCY = REGISTRY.histogram(
    "bqt_fanout_write_latency_ms",
    "Subscriber match→socket-write latency per transport: the device "
    "match dispatch that selected the recipient to the frame leaving "
    "for that connection's socket.",
    labels=("transport",),
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
             1000.0, 5000.0),
)
SLO_BURNING = REGISTRY.gauge(
    "bqt_slo_burning",
    "Whether the named SLO is currently burning (1) or clean (0) in the "
    "unified registry (obs/slo.py) — freshness / staleness / "
    "delivery.<sink>.",
    labels=("slo",),
)
SLO_BREACHES = REGISTRY.counter(
    "bqt_slo_breaches_total",
    "Failing observations per registered SLO (burn entry force-emits an "
    "slo_burn event; re-emits ride the BQT_SLO_EVENT_EVERY cadence).",
    labels=("slo",),
)
SLO_RECOVERIES = REGISTRY.counter(
    "bqt_slo_recoveries_total",
    "Burn→clean transitions per registered SLO (each emits an "
    "slo_recover event carrying the burn length).",
    labels=("slo",),
)
