"""Host-pipeline behavior pins: live-set gating, per-bar dedupe, ingest
validation, BTC row-0 resolution, and same-timestamp regime staging.

These target the round-1 advisor/judge findings: dormant strategies must
not emit unless enabled, a standing trigger must fire once per bar despite
1 s re-ticks, non-5m/15m frames must be rejected, registry row 0 is a valid
BTC row, and mid-bucket context refinements must not fire spurious
transitions.
"""

import numpy as np
import pandas as pd
import pytest

from binquant_tpu.io.emission import LIVE_STRATEGIES, extract_fired
from binquant_tpu.io.replay import make_stub_engine
from binquant_tpu.engine.step import STRATEGY_ORDER
from tests.test_engine_step import (
    CFG,
    S_CAP,
    WINDOW,
    frames_to_updates,
)
from tests.conftest import make_ohlcv


@pytest.fixture(scope="module")
def tick_outputs():
    """One real tick at the shared (16, 130) shape (compile cache hit)."""
    import jax.numpy as jnp

    from binquant_tpu.engine.step import (
        default_host_inputs,
        initial_engine_state,
        pad_updates,
        tick_step,
    )

    rng = np.random.default_rng(99)
    frames = {
        i: pd.DataFrame(make_ohlcv(rng, n=WINDOW, start_price=30 + i, vol=0.006))
        for i in range(8)
    }
    state = initial_engine_state(S_CAP, window=WINDOW)
    tracked = np.zeros(S_CAP, dtype=bool)
    tracked[:8] = True
    out = None
    for b in range(WINDOW):
        upd = pad_updates(*frames_to_updates(frames, b), size=S_CAP)
        ts = int(frames[0]["open_time"].iloc[b]) // 1000
        inputs = default_host_inputs(S_CAP)._replace(
            tracked=jnp.asarray(tracked),
            btc_row=np.int32(0),
            timestamp_s=np.int32(ts),
            timestamp5_s=np.int32(ts),
        )
        state, out = tick_step(state, upd, upd, inputs, CFG)
    return out


def _forced_unpacked(outputs, strategy: str, row: int):
    """Synthetic unpack_wire result with one fired (strategy, row) entry."""
    from binquant_tpu.engine.step import WireFired
    from binquant_tpu.strategies.market_regime_notifier import context_scalars

    si = STRATEGY_ORDER.index(strategy)
    fired = WireFired(
        n=1,
        overflow=False,
        strategy_idx=np.array([si], np.int32),
        row=np.array([row], np.int32),
        autotrade=np.array([True]),
        direction=np.array([0], np.int32),
        score=np.array([1.0], np.float32),
        stop_loss_pct=np.array([0.0], np.float32),
    )
    return fired, context_scalars(outputs.context)


class FakeRegistry:
    def name_of(self, row):
        return f"S{row:03d}USDT"


class TestLiveSetGating:
    def test_dormant_strategy_not_emitted_by_default(self, tick_outputs):
        unp = _forced_unpacked(tick_outputs, "coinrule_buy_the_dip", 2)
        fired = extract_fired(tick_outputs, FakeRegistry(), unpacked=unp)
        assert all(f.strategy != "coinrule_buy_the_dip" for f in fired)

    def test_dormant_strategy_emitted_when_enabled(self, tick_outputs):
        unp = _forced_unpacked(tick_outputs, "coinrule_buy_the_dip", 2)
        fired = extract_fired(
            tick_outputs,
            FakeRegistry(),
            enabled=LIVE_STRATEGIES | {"coinrule_buy_the_dip"},
            unpacked=unp,
        )
        assert any(
            f.strategy == "coinrule_buy_the_dip" and f.row == 2 for f in fired
        )

    def test_live_strategy_emitted_by_default(self, tick_outputs):
        unp = _forced_unpacked(tick_outputs, "mean_reversion_fade", 3)
        fired = extract_fired(tick_outputs, FakeRegistry(), unpacked=unp)
        assert any(f.strategy == "mean_reversion_fade" and f.row == 3 for f in fired)

    def test_wire_roundtrip_matches_context(self, tick_outputs):
        """unpack_wire(outputs.wire) == the directly-fetched context scalars."""
        from binquant_tpu.engine.step import unpack_wire
        from binquant_tpu.strategies.market_regime_notifier import context_scalars

        fired_w, ctx_w = unpack_wire(tick_outputs.wire)
        ctx_direct = context_scalars(tick_outputs.context)
        for k, v in ctx_direct.items():
            if isinstance(v, float):
                assert abs(ctx_w[k] - v) < 1e-5, k
            else:
                assert ctx_w[k] == v, k
        # no dormant strategy occupies a wire slot
        for si in fired_w.strategy_idx:
            assert STRATEGY_ORDER[int(si)] in LIVE_STRATEGIES

    def test_live_set_matches_reference_dispatch(self):
        # context_evaluator.py:369-479 dispatches ABP + PriceTracker (5m),
        # LSP + MRF + LadderDeployer (15m); SpikeHunter disabled.
        assert LIVE_STRATEGIES == {
            "activity_burst_pump",
            "coinrule_price_tracker",
            "liquidation_sweep_pump",
            "mean_reversion_fade",
            "grid_ladder",
        }


class TestPerBarDedupe:
    def _fake_signal(self, strategy, row):
        from binquant_tpu.io.emission import FiredSignal

        return FiredSignal(strategy, f"S{row}", row, None, "", {})

    def test_second_tick_same_bar_suppressed(self):
        eng = make_stub_engine(capacity=16, window=64)
        sigs = [self._fake_signal("liquidation_sweep_pump", 1)]
        kept1 = eng._dedupe_fired(list(sigs), ts5=1000, ts15=9000)
        kept2 = eng._dedupe_fired(list(sigs), ts5=1000, ts15=9000)
        assert len(kept1) == 1
        assert len(kept2) == 0

    def test_new_bar_re_emits(self):
        eng = make_stub_engine(capacity=16, window=64)
        sigs = [self._fake_signal("liquidation_sweep_pump", 1)]
        assert len(eng._dedupe_fired(list(sigs), ts5=1000, ts15=9000)) == 1
        assert len(eng._dedupe_fired(list(sigs), ts5=1000, ts15=9900)) == 1

    def test_5m_strategy_keys_on_5m_bucket(self):
        eng = make_stub_engine(capacity=16, window=64)
        sigs = [self._fake_signal("activity_burst_pump", 4)]
        assert len(eng._dedupe_fired(list(sigs), ts5=1000, ts15=9000)) == 1
        # same 15m bucket but a NEW 5m bar -> re-emits
        assert len(eng._dedupe_fired(list(sigs), ts5=1300, ts15=9000)) == 1
        # same 5m bar again -> suppressed
        assert len(eng._dedupe_fired(list(sigs), ts5=1300, ts15=9000)) == 0


class TestIngestValidation:
    def _kline(self, duration_s, symbol="AAAUSDT"):
        t0 = 1_753_000_000_000
        return {
            "symbol": symbol,
            "open_time": t0,
            "close_time": t0 + duration_s * 1000 - 1,
            "open": 1.0,
            "high": 1.1,
            "low": 0.9,
            "close": 1.05,
            "volume": 10.0,
            "quote_asset_volume": 10.5,
            "number_of_trades": 5,
            "taker_buy_base_volume": 5.0,
            "taker_buy_quote_volume": 5.2,
        }

    def test_5m_and_15m_routed(self):
        eng = make_stub_engine(capacity=16, window=64)
        eng.ingest(self._kline(300))
        eng.ingest(self._kline(900))
        assert len(eng.batcher5) == 1
        assert len(eng.batcher15) == 1

    def test_other_durations_rejected(self):
        eng = make_stub_engine(capacity=16, window=64)
        eng.ingest(self._kline(60))
        eng.ingest(self._kline(3600))
        assert len(eng.batcher5) == 0
        assert len(eng.batcher15) == 0


def test_btc_row_zero_not_treated_as_missing():
    eng = make_stub_engine(capacity=16, window=64)
    row = eng.registry.add("BTCUSDT")
    assert row == 0
    # reproduce the resolution logic used by process_tick
    _btc = eng.registry.row_of(eng.btc_symbol)
    btc_row = -1 if _btc is None else int(_btc)
    assert btc_row == 0


class TestRegimeStaging:
    """Same-timestamp refinements must not promote the carry
    (reference _get_previous_context skips known_timestamp >= timestamp)."""

    def test_same_ts_refinement_has_no_previous(self):
        from tests.test_regime_context import (
            build_market,
            load_buffer,
            run_kernel,
        )
        from binquant_tpu.regime import ContextConfig

        rng = np.random.default_rng(31)
        cfg = ContextConfig(required_fresh_symbols=4, min_coverage_ratio=0.5)
        market = build_market(rng, n_symbols=8, n_bars=60, drift=0.004)
        buf, rows, ts0 = load_buffer(market)
        ctx1, carry1 = run_kernel(buf, rows, ts0, cfg=cfg)
        assert bool(ctx1.valid)
        assert int(ctx1.previous_market_regime) == -1

        # refinement at the SAME timestamp with crashed closes: still no
        # strictly-older context -> no previous, no transition event
        crash = {}
        for s, df in market.items():
            df = df.copy()
            df.loc[df.index[-1], "close"] = float(df["close"].iloc[-2]) * 0.91
            df.loc[df.index[-1], "low"] = float(df["close"].iloc[-1]) * 0.99
            crash[s] = df
        buf2, _, _ = load_buffer(crash)
        ctx2, carry2 = run_kernel(buf2, rows, ts0, carry=carry1, cfg=cfg)
        assert bool(ctx2.valid)
        assert int(ctx2.previous_market_regime) == -1
        assert int(ctx2.market_regime_transition) == -1

        # a strictly newer tick promotes the LATEST refinement (ctx2), not
        # the first evaluation
        nxt = {}
        for s, df in crash.items():
            last = df.iloc[-1]
            t1 = int(last["open_time"]) + 900_000
            row = dict(last)
            px = float(last["close"]) * 1.002
            row.update(
                open_time=t1, close_time=t1 + 899_999, open=last["close"],
                high=px * 1.001, low=float(last["close"]) * 0.999, close=px,
            )
            nxt[s] = pd.concat([df, pd.DataFrame([row])], ignore_index=True)
        buf3, rows3, ts1 = load_buffer(nxt)
        ctx3, _ = run_kernel(buf3, rows3, ts1, carry=carry2, cfg=cfg)
        assert bool(ctx3.valid)
        assert int(ctx3.previous_market_regime) == int(ctx2.market_regime)


class TestDeviceInputCaches:
    """Per-tick HostInputs churn (r3): device scalars are re-uploaded only
    when values change; the tracked mask only on registry membership
    changes; NaN-valued scalars must count as cache hits (NaN != NaN would
    otherwise re-upload every tick)."""

    def _engine(self):
        from binquant_tpu.io.replay import make_stub_engine

        return make_stub_engine(capacity=8, window=40)

    def test_dev_scalar_value_cache_nan_stable(self):
        engine = self._engine()
        a = engine._dev_scalar("adp_latest", np.float32("nan"))
        b = engine._dev_scalar("adp_latest", np.float32("nan"))
        assert a is b  # NaN == NaN counts as a hit
        c = engine._dev_scalar("adp_latest", np.float32(0.25))
        assert c is not b
        assert float(c) == 0.25
        d = engine._dev_scalar("adp_latest", np.float32(0.25))
        assert d is c

    def test_dev_scalar_bool_flags(self):
        engine = self._engine()
        t1 = engine._dev_scalar("quiet_hours", True)
        f1 = engine._dev_scalar("quiet_hours", False)
        assert bool(t1) is True and bool(f1) is False
        assert engine._dev_scalar("quiet_hours", False) is f1

    def test_tracked_mask_invalidated_by_registry_changes(self):
        engine = self._engine()
        engine.registry.add("AUSDT")
        m1 = engine._tracked_mask()
        assert engine._tracked_mask() is m1  # no membership change: cached
        engine.registry.add("BUSDT")
        m2 = engine._tracked_mask()
        assert m2 is not m1
        assert int(np.asarray(m2).sum()) == 2
        engine.registry.remove("AUSDT")
        m3 = engine._tracked_mask()
        assert m3 is not m2
        assert int(np.asarray(m3).sum()) == 1


def test_legacy_emission_handles_scalar_diagnostics(tick_outputs):
    """The overflow/fabricated-wire fallback indexes diagnostics per row;
    market-wide scalar diagnostics (0-d arrays — PriceTracker's
    breadth_stable and confidence are the real cases) must resolve to the
    shared value instead of raising (r3 regression found by the
    4096-symbol bench's overflow ticks)."""
    so = tick_outputs.strategies["coinrule_price_tracker"]
    assert any(
        np.asarray(v).ndim == 0 for v in so.diagnostics.values()
    ), "fixture lost its 0-d diagnostic; the test would go vacuous"
    unp = _forced_unpacked(tick_outputs, "coinrule_price_tracker", 2)
    fired = extract_fired(tick_outputs, FakeRegistry(), unpacked=unp)
    sig = next(f for f in fired if f.strategy == "coinrule_price_tracker")
    assert "confidence" in sig.analytics["indicators"]
