"""Binbot backend REST client.

Equivalent surface to the pybinbot ``BinbotApi`` the reference consumes
(SURVEY.md §2.8): symbols/settings, bot lifecycle (real + paper), grid
ladders, analytics dispatch, and market breadth. Thin JSON-over-HTTP with
an injectable session so the whole surface is mockable — the reference's
tests patch ``BinbotApi`` wholesale (tests/conftest.py:34-49) and ours do
the same at this class.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Any

from binquant_tpu.exceptions import BinbotError
from binquant_tpu.obs.instruments import BINBOT_REQUESTS, BINBOT_RETRIES
from binquant_tpu.schemas import (
    AutotradeSettingsSchema,
    MarketBreadthSeries,
    SymbolModel,
    TestAutotradeSettingsSchema,
)


class BinbotApi:
    """Endpoints mirror the reference's consumption sites
    (consumers/klines_provider.py, consumers/autotrade_consumer.py,
    shared/autotrade.py)."""

    def __init__(
        self,
        base_url: str,
        session: Any | None = None,
        timeout_s: float = 10.0,
        retry_max: int = 0,
        retry_backoff_s: float = 0.2,
        rng: random.Random | None = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        if session is None:
            import httpx

            session = httpx.Client(timeout=timeout_s)
        self.session = session
        # bounded REST calls (ISSUE 13 satellite): every request carries a
        # deadline (the client timeout above) and up to ``retry_max``
        # in-client retries after a transport error or 5xx, with jittered
        # exponential backoff. Exhaustion is COUNTED (metric + event) and
        # the error then propagates as before — never a silent hang, and
        # no crash-ring entry on the emission path (the span records the
        # error without flagging the trace).
        self.timeout_s = float(timeout_s)
        self.retry_max = max(int(retry_max), 0)
        self.retry_backoff_s = float(retry_backoff_s)
        self._rng = rng or random.Random()

    # -- plumbing -----------------------------------------------------------

    def _request(self, method: str, path: str, **kwargs) -> Any:
        """One REST round trip. When a tick trace is active (the call is
        on the tick's emission path), the request gets its own span —
        attributed HTTP latency per backend call, joined to the producing
        tick by trace_id — and failures mark the span (and therefore the
        trace) errored. Off-tick calls (boot, background workers) see only
        the counters, as before."""
        from binquant_tpu.obs.tracing import current_trace

        trace = current_trace()
        if trace is None:
            return self._request_inner(method, path, **kwargs)
        with trace.span(
            f"binbot.{method.lower()}", path=path
        ) as span:
            try:
                payload = self._request_inner(method, path, **kwargs)
            except Exception as exc:
                span.set(error=str(exc))
                raise
            return payload

    def _request_inner(self, method: str, path: str, **kwargs) -> Any:
        """One bounded round trip: transport errors and 5xx responses are
        retried up to ``retry_max`` times with jittered exponential
        backoff (4xx and backend-error bodies are NOT — they are
        deterministic rejections, not weather). Exhaustion counts in
        bqt_binbot_retries_total{outcome=exhausted} and emits a
        binbot_retry_exhausted event before the final error propagates."""
        url = f"{self.base_url}{path}"
        attempts = self.retry_max + 1
        backoff = self.retry_backoff_s
        for attempt in range(attempts):
            retryable: str | None = None
            try:
                resp = self.session.request(method, url, **kwargs)
            except Exception:
                BINBOT_REQUESTS.labels(
                    method=method, outcome="transport_error"
                ).inc()
                retryable = "transport_error"
                if attempt + 1 >= attempts:
                    if self.retry_max:
                        self._note_exhausted(method, path, retryable)
                    raise
            else:
                if resp.status_code >= 500:
                    BINBOT_REQUESTS.labels(
                        method=method, outcome="http_error"
                    ).inc()
                    retryable = f"http_{resp.status_code}"
                    if attempt + 1 >= attempts:
                        if self.retry_max:
                            self._note_exhausted(method, path, retryable)
                        raise BinbotError(
                            f"{method} {path} -> {resp.status_code}: {resp.text}"
                        )
                elif resp.status_code >= 400:
                    BINBOT_REQUESTS.labels(
                        method=method, outcome="http_error"
                    ).inc()
                    raise BinbotError(
                        f"{method} {path} -> {resp.status_code}: {resp.text}"
                    )
                else:
                    payload = resp.json()
                    if isinstance(payload, dict) and payload.get("error") == 1:
                        BINBOT_REQUESTS.labels(
                            method=method, outcome="backend_error"
                        ).inc()
                        raise BinbotError(
                            str(payload.get("message", "unknown binbot error"))
                        )
                    BINBOT_REQUESTS.labels(method=method, outcome="ok").inc()
                    return payload
            # jittered backoff before the retry (websocket reconnect_delay
            # idiom — a fleet of clients must not re-storm the backend)
            from binquant_tpu.io.websocket import reconnect_delay

            BINBOT_RETRIES.labels(outcome="retry").inc()
            time.sleep(reconnect_delay(backoff, self._rng))
            backoff *= 2.0
        raise BinbotError(f"{method} {path}: retry loop exited")  # unreachable

    def _note_exhausted(self, method: str, path: str, reason: str) -> None:
        from binquant_tpu.obs.events import get_event_log

        BINBOT_RETRIES.labels(outcome="exhausted").inc()
        get_event_log().emit(
            "binbot_retry_exhausted",
            method=method,
            path=path,
            reason=reason,
            retries=self.retry_max,
        )

    def _get(self, path: str, **kwargs) -> Any:
        return self._request("GET", path, **kwargs)

    def _post(self, path: str, json: Any = None, **kwargs) -> Any:
        return self._request("POST", path, json=json, **kwargs)

    def _put(self, path: str, json: Any = None, **kwargs) -> Any:
        return self._request("PUT", path, json=json, **kwargs)

    def _delete(self, path: str, **kwargs) -> Any:
        return self._request("DELETE", path, **kwargs)

    @staticmethod
    def _data(payload: Any) -> Any:
        if isinstance(payload, dict) and "data" in payload:
            return payload["data"]
        return payload

    # -- symbols & settings -------------------------------------------------

    def get_symbols(self) -> list[SymbolModel]:
        rows = self._data(self._get("/symbols"))
        return [SymbolModel.model_validate(r) for r in rows]

    def get_single_symbol(self, symbol: str) -> SymbolModel:
        row = self._data(self._get(f"/symbol/{symbol}"))
        return SymbolModel.model_validate(row)

    def edit_symbol(self, symbol: str, **fields: Any) -> Any:
        return self._put(f"/symbol/{symbol}", json=fields)

    def get_autotrade_settings(self) -> AutotradeSettingsSchema:
        row = self._data(self._get("/autotrade-settings/bots"))
        return AutotradeSettingsSchema.model_validate(row)

    def get_test_autotrade_settings(self) -> TestAutotradeSettingsSchema:
        row = self._data(self._get("/autotrade-settings/paper-trading"))
        return TestAutotradeSettingsSchema.model_validate(row)

    def filter_excluded_symbols(self) -> list[str]:
        return list(self._data(self._get("/symbols/excluded")) or [])

    # -- bots (real + paper) ------------------------------------------------

    def create_bot(self, payload: dict) -> Any:
        return self._post("/bot", json=payload)

    def activate_bot(self, bot_id: str) -> Any:
        return self._get(f"/bot/activate/{bot_id}")

    def deactivate_bot(self, bot_id: str, algorithmic_close: bool = False) -> Any:
        return self._delete(
            f"/bot/deactivate/{bot_id}",
            params={"algorithmic_close": algorithmic_close},
        )

    def create_paper_bot(self, payload: dict) -> Any:
        return self._post("/paper-trading", json=payload)

    def activate_paper_bot(self, bot_id: str) -> Any:
        return self._get(f"/paper-trading/activate/{bot_id}")

    def delete_paper_bot(self, bot_id: str) -> Any:
        return self._delete(f"/paper-trading/{bot_id}")

    def get_active_pairs(self, collection_name: str = "bots") -> list[str]:
        return list(self._data(self._get(f"/bots/active-pairs/{collection_name}")) or [])

    def submit_bot_event_logs(self, bot_id: str, message: str) -> Any:
        try:
            return self._post(f"/bot/errors/{bot_id}", json={"errors": message})
        except BinbotError:
            logging.exception("submit_bot_event_logs failed for %s", bot_id)
            return None

    def submit_paper_trading_event_logs(self, bot_id: str, message: str) -> Any:
        try:
            return self._post(
                f"/paper-trading/errors/{bot_id}", json={"errors": message}
            )
        except BinbotError:
            logging.exception("submit_paper_trading_event_logs failed for %s", bot_id)
            return None

    def clean_margin_short(self, pair: str) -> Any:
        return self._get(f"/bot/clean-margin-short/{pair}")

    def get_available_fiat(self, exchange: str, fiat: str = "USDT") -> float:
        data = self._data(
            self._get("/balance/available-fiat", params={"exchange": exchange, "fiat": fiat})
        )
        if isinstance(data, dict):
            return float(data.get("amount", 0.0))
        return float(data or 0.0)

    # -- grid ladders -------------------------------------------------------

    def get_active_grid_ladders(self) -> list[dict]:
        return list(self._data(self._get("/grid-ladders/active")) or [])

    def calculate_grid_levels(self, payload: dict) -> Any:
        return self._post("/grid-ladders/calculate", json=payload)

    def create_grid_ladder(self, payload: dict) -> Any:
        return self._post("/grid-ladders", json=payload)

    # -- analytics ----------------------------------------------------------

    def dispatch_create_signal(self, payload: dict) -> Any:
        """Analytics record for EVERY strategy emission
        (producers/context_evaluator.py:268-333)."""
        return self._post("/signals", json=payload)

    # -- market data --------------------------------------------------------

    async def get_market_breadth(self, size: int = 7) -> MarketBreadthSeries:
        """Async in the reference; sync transport wrapped for interface
        parity."""
        data = self._data(self._get("/market-breadth", params={"size": size}))
        return MarketBreadthSeries.model_validate(data or {})
