"""Reference differential (VERDICT r4 item 1): execute the REFERENCE code.

Every A/B in this suite compares the TPU batch path against a
builder-transcribed pandas oracle; a transcription error would leave both
sides green. These tests close that hole by importing /root/reference's
own strategy + regime + provider modules (``binquant_tpu/refdiff``), with
ONLY the external pybinbot SDK shimmed, replaying the same fixtures, and
asserting the three backends emit the IDENTICAL signal set and regime
trace:

    reference (verbatim)  ==  transcribed oracle  ==  TPU batch path

Matches: /root/reference/strategies/mean_reversion_fade.py:79-151,
/root/reference/market_regime/regime_transitions.py:50-101,
/root/reference/producers/context_evaluator.py:335-481 and the rest of the
live dispatch chain.

Full-breadth (100-symbol) runs live in tools/run_reference_differential.py
(writes REFDIFF.json); the suite uses bounded fixtures to keep the slow
lane's wall-clock sane.
"""

from __future__ import annotations

import gzip
import json
from pathlib import Path

import pytest

from binquant_tpu.io.replay import (
    generate_replay_file,
    load_klines_by_tick,
    run_replay,
    run_replay_oracle,
)
from binquant_tpu.refdiff import reference_available, run_replay_reference

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not reference_available(),
        reason="reference tree not present (BQT_REFERENCE_PATH)",
    ),
]

CAPACITY, WINDOW = 64, 200
FIXTURE = Path(__file__).parent / "fixtures" / "market_36h_100sym.jsonl.gz"

# same scripted breadth the A/B uses: engages LSP's LONG route and the
# grid-only policy (tests/test_ab_parity.py)
WASHED_BREADTH = {
    "timestamp": [1, 2, 3],
    "market_breadth": [-0.50, -0.47, -0.44],
    "market_breadth_ma": [-0.50, -0.46],
}


@pytest.fixture(scope="module")
def replay_path(tmp_path_factory):
    path = tmp_path_factory.mktemp("refdiff") / "ab_7.jsonl"
    generate_replay_file(path, n_symbols=24, n_ticks=120, seed=7)
    return path


def test_reference_matches_both_backends_with_breadth(replay_path):
    """Three-way set equality on the crafted A/B replay, breadth scripted
    so all five live strategies engage — the reference's own code is the
    arbiter."""
    ref_regimes: list = []
    ref = set(
        run_replay_reference(
            replay_path,
            window=WINDOW,
            breadth=WASHED_BREADTH,
            collect_regimes=ref_regimes,
        )
    )
    orc_regimes: list = []
    orc = set(
        run_replay_oracle(
            replay_path,
            window=WINDOW,
            breadth=WASHED_BREADTH,
            collect_regimes=orc_regimes,
        )
    )
    tpu_list: list = []
    run_replay(
        replay_path,
        capacity=CAPACITY,
        window=WINDOW,
        collect=tpu_list,
        breadth=WASHED_BREADTH,
    )
    tpu = set(tpu_list)

    assert ref == orc, {
        "only_ref": sorted(ref - orc)[:5],
        "only_oracle": sorted(orc - ref)[:5],
    }
    assert ref == tpu, {
        "only_ref": sorted(ref - tpu)[:5],
        "only_tpu": sorted(tpu - ref)[:5],
    }
    # non-vacuous: every live strategy must actually have fired in the
    # matching set (mirrors test_ab_parity's coverage guard)
    strategies = {s for _, s, *_ in ref}
    assert {
        "activity_burst_pump",
        "coinrule_price_tracker",
        "liquidation_sweep_pump",
        "mean_reversion_fade",
        "grid_ladder",
    } <= strategies, strategies

    # regime trace: the reference's RegimeTransitionDetector output per
    # tick must equal the oracle's ladder (labels + strength)
    assert len(ref_regimes) == len(orc_regimes)
    for (t_r, label_r, strength_r), (t_o, label_o, strength_o) in zip(
        ref_regimes, orc_regimes
    ):
        assert t_r == t_o
        assert label_r == label_o, (t_r, label_r, label_o)
        assert strength_r == pytest.approx(strength_o, abs=1e-9), t_r
    # the trace must include real classifications, not wall-to-wall None
    assert sum(1 for _, label, _ in ref_regimes if label is not None) > 50


def test_reference_matches_tpu_on_market_fixture_subset(tmp_path):
    """The realistic 36h market fixture through the reference chain vs the
    TPU path, on a 24-symbol × 125-bucket subset (the reference re-enriches
    every symbol per bucket, so its cost scales with S×T×W — this keeps the
    slow lane's wall-clock sane; the full 100-symbol diff is
    tools/run_reference_differential.py → REFDIFF.json)."""
    by_tick = load_klines_by_tick(FIXTURE)
    symbols = sorted({k["symbol"] for ks in by_tick.values() for k in ks})
    subset = set(symbols[:23]) | {"BTCUSDT"}
    buckets = set(sorted(by_tick)[:125])
    sub_path = tmp_path / "fixture_subset.jsonl"
    with gzip.open(FIXTURE, "rt") as f, open(sub_path, "w") as out:
        for line in f:
            k = json.loads(line)
            if k["symbol"] in subset and k["open_time"] // 1000 // 900 in buckets:
                out.write(line)

    window = 150  # >= MIN_BARS=100 with headroom; trimmed for pandas cost
    ref = set(run_replay_reference(sub_path, window=window))
    tpu_list: list = []
    run_replay(sub_path, capacity=32, window=window, collect=tpu_list)
    tpu = set(tpu_list)
    assert ref == tpu, {
        "only_ref": sorted(ref - tpu)[:5],
        "only_tpu": sorted(tpu - ref)[:5],
    }
    # an eventful 36h market must fire signals on this subset, or the
    # equality is vacuous
    assert len(ref) > 10


def test_reference_own_suite_passes_against_sdk_replica():
    """The reference's ENTIRE unit suite (~240 tests) runs against this
    repo's pybinbot-surface replica via the refdiff shims — behavioral
    compatibility of the SDK layer proven by the reference's own
    expectations, not ours (tools/run_reference_suite.py)."""
    import subprocess
    import sys
    from pathlib import Path

    script = Path(__file__).parent.parent / "tools" / "run_reference_suite.py"
    # NOTE: the wrapper already passes -q; adding another would make the
    # inner pytest -qq, which suppresses the final count line entirely
    proc = subprocess.run(
        [sys.executable, str(script), "--no-header"],
        capture_output=True,
        text=True,
        timeout=600,
    )
    # returncode is authoritative (pytest exits nonzero on any failure);
    # additionally require a real pass count somewhere in the output so a
    # zero-collected run can't satisfy this vacuously
    tail = "\n".join(proc.stdout.splitlines()[-15:])
    assert proc.returncode == 0, tail
    import re

    m = re.search(r"(\d+) passed", proc.stdout)
    assert m and int(m.group(1)) >= 200, tail


DORMANT_BREADTH = {
    "timestamp": [1, 2, 3, 4],
    "market_breadth": [0.30, 0.34, 0.38, 0.42],
    "market_breadth_ma": [0.30, 0.36],
}


def test_reference_dormant_core_set_matches(tmp_path):
    """The dormant strategies are not dispatched by the reference's
    current evaluator, but their classes remain fully wired to it; the
    harness reconstructs the retired dispatch (refdiff/driver.py
    _dormant_dispatch_wrapper) and their signal bodies execute verbatim.
    Core set (BuyTheDip / BBExtremeReversion / RangeBbRsiMeanReversion —
    the inline-indicator transcription risks of VERDICT r2 item 6) must
    match both backends."""
    from binquant_tpu.io.replay import generate_dormant_replay
    from binquant_tpu.oracle.evaluator import DORMANT_ORACLE_STRATEGIES

    path = tmp_path / "dormant.jsonl"
    generate_dormant_replay(path)
    dorm = set(DORMANT_ORACLE_STRATEGIES)
    ref = {
        t
        for t in run_replay_reference(path, window=WINDOW, dispatch_dormant=True)
        if t[1] in dorm
    }
    orc = {
        t
        for t in run_replay_oracle(path, window=WINDOW, enabled_strategies=dorm)
        if t[1] in dorm
    }
    tpu_list: list = []
    run_replay(
        path, capacity=CAPACITY, window=WINDOW, collect=tpu_list,
        enabled_strategies=dorm,
    )
    tpu = {t for t in tpu_list if t[1] in dorm}
    assert ref == orc == tpu, {
        "only_ref": sorted(ref - orc)[:5],
        "only_orc": sorted(orc - ref)[:5],
        "only_tpu": sorted(tpu - ref)[:5],
    }
    assert {s for _, s, *_ in ref} == dorm  # all three engaged


def test_reference_dormant_extended_set_matches(tmp_path):
    """Extended dormant set (TWAP sniper, supertrend swing reversal,
    buy-low-sell-high, inverse price tracker, RS reversal range, range
    failed-breakout fade) — every one of the 14 strategy kernels now
    diffs against the reference's own executed code. Exercises the
    dropna-seeded supertrend (ops supertrend_from) and the dominance
    scripting."""
    from binquant_tpu.io.replay import generate_dormant_extended_replay
    from binquant_tpu.oracle.evaluator import DORMANT_ORACLE_EXTENDED

    path = tmp_path / "dormant_ext.jsonl"
    generate_dormant_extended_replay(path)
    dorm = set(DORMANT_ORACLE_EXTENDED)
    kwargs = dict(
        breadth=DORMANT_BREADTH,
        dominance_is_losers=True,
        market_domination_reversal=True,
    )
    ref = {
        t
        for t in run_replay_reference(
            path, window=WINDOW, dispatch_dormant=True, **kwargs
        )
        if t[1] in dorm
    }
    orc = {
        t
        for t in run_replay_oracle(
            path, window=WINDOW, enabled_strategies=dorm, **kwargs
        )
        if t[1] in dorm
    }
    tpu_list: list = []
    run_replay(
        path, capacity=CAPACITY, window=WINDOW, collect=tpu_list,
        enabled_strategies=dorm, **kwargs,
    )
    tpu = {t for t in tpu_list if t[1] in dorm}
    assert ref == orc == tpu, {
        "only_ref": sorted(ref - orc)[:5],
        "only_orc": sorted(orc - ref)[:5],
        "only_tpu": sorted(tpu - ref)[:5],
    }
    assert {s for _, s, *_ in ref} == dorm


def test_reference_leverage_calibrator_matches():
    """SURVEY row 22 (leverage calibrator): the REFERENCE's own
    LeverageCalibrator executes verbatim over contexts built by its own
    accumulator, and its edit decisions must equal this repo's calibrator
    on the same inputs (vectorized ladder + FrozenRows snapshot)."""
    import numpy as np

    from binquant_tpu.engine.buffer import FrozenRows
    from binquant_tpu.io.leverage import CalibrationInputs
    from binquant_tpu.io.leverage import LeverageCalibrator as MyCalibrator
    from binquant_tpu.schemas import SymbolModel as MySymbolModel
    from binquant_tpu.refdiff.shims import install_shims
    from binquant_tpu.enums import MarketRegimeCode

    install_shims()
    import pandas as pd
    import pybinbot
    from calibrators.leverage_calibrator import LeverageCalibrator as RefCalibrator
    from market_regime.live_market_context_accumulator import (
        LiveMarketContextAccumulator,
    )
    from market_regime.market_state_store import MarketStateStore

    rng = np.random.default_rng(77)
    n_sym, n_bars = 60, 60
    names = ["BTCUSDT"] + [f"S{i:03d}USDT" for i in range(1, n_sym)]

    def build_context(drift: float, vol: float):
        store = MarketStateStore(max_bars_per_symbol=200)
        acc = LiveMarketContextAccumulator(state_store=store, btc_symbol="BTCUSDT")
        t0 = 1_780_272_000_000
        for s, name in enumerate(names):
            # price levels straddle the 500 price-high threshold; per-symbol
            # vol straddles the 4% atr_pct threshold
            base = [40.0, 120.0, 480.0, 510.0, 800.0][s % 5]
            v = vol * (0.3 + 2.2 * (s % 7) / 6)
            closes = base * np.exp(np.cumsum(rng.normal(drift, v, n_bars)))
            df = pd.DataFrame(
                {
                    "timestamp": t0 + 900_000 * np.arange(n_bars),
                    "open": np.r_[base, closes[:-1]],
                    "high": closes * (1 + v),
                    "low": closes * (1 - v),
                    "close": closes,
                    "volume": 1000.0,
                }
            )
            store.update(symbol=name, candle=df)
        ctx = acc.refresh_context_for_timestamp(int(t0 + 900_000 * (n_bars - 1)))
        assert ctx is not None
        return ctx

    class _Recorder:
        def __init__(self):
            self.edits = {}

        def edit_symbol(self, symbol=None, **kw):
            self.edits[symbol] = kw["futures_leverage"]

    scenarios = [
        ("calm_range", build_context(drift=0.0, vol=0.004)),
        ("stressed", build_context(drift=-0.02, vol=0.02)),
        ("trending", build_context(drift=0.01, vol=0.006)),
    ]
    # exercise the confidence floor too
    low_conf = scenarios[0][1].model_copy(update={"confidence": 0.3})
    scenarios.append(("low_confidence", low_conf))

    regime_code = {r.name: int(r) for r in MarketRegimeCode}
    total_edits = 0
    for label, ctx in scenarios:
        ref_rec = _Recorder()
        ref_cal = RefCalibrator(binbot_api=ref_rec, exchange=pybinbot.ExchangeId.KUCOIN)
        ref_symbols = [pybinbot.SymbolModel(id=n, futures_leverage=1) for n in names]
        ref_cal.calibrate_all(ctx, ref_symbols)

        # my calibrator on the SAME inputs: rows in name order
        feats = ctx.symbol_features
        valid = np.array([n in feats for n in names])
        closes = np.array([feats[n].close if n in feats else np.nan for n in names])
        atrs = np.array([feats[n].atr_pct if n in feats else np.nan for n in names])
        my_rec = _Recorder()

        class _Api:
            def edit_symbol(self, symbol, **kw):
                my_rec.edits[symbol] = kw["futures_leverage"]

        my_cal = MyCalibrator(binbot_api=_Api(), exchange="kucoin")
        my_symbols = [MySymbolModel(id=n, futures_leverage=1) for n in names]
        my_cal.calibrate_all(
            CalibrationInputs(
                valid=valid,
                close=closes,
                atr_pct=atrs,
                regime=regime_code[ctx.market_regime],
                stress=float(ctx.market_stress_score),
                confidence=float(ctx.confidence),
            ),
            FrozenRows({i: n for i, n in enumerate(names)}),
            my_symbols,
        )
        total_edits += len(ref_rec.edits)
        assert ref_rec.edits == my_rec.edits, (
            label,
            {k: (ref_rec.edits.get(k), my_rec.edits.get(k))
             for k in set(ref_rec.edits) ^ set(my_rec.edits)
             | {k for k in set(ref_rec.edits) & set(my_rec.edits)
                if ref_rec.edits[k] != my_rec.edits[k]}},
        )
    # non-vacuous: the scenarios must actually have produced edits
    # (ref_rec is rebuilt per scenario; the loop asserted equality each
    # time, so checking the final one plus total coverage suffices)
    assert total_edits > 0
