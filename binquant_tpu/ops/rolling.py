"""Rolling-window primitives along the last axis, pandas-parity semantics.

Every function takes ``(..., W)`` arrays and is jit-safe with static window
params, so a batched ``(S, W)`` market buffer needs no vmap. NaN encodes
"missing/warm-up" exactly as pandas does: rolling reducers are NaN-aware and
honour ``min_periods``.

TPU-first choices:

* **EWM is a matmul, not a scan.** ``y = A @ x`` with a cached lower-triangular
  decay matrix runs on the MXU in one pass; an exact per-row correction term
  reproduces pandas' ``adjust=False`` recursion (first valid sample seeds the
  carry) without any sequential dependency. The reference computes every EMA
  with ``pandas.ewm`` per symbol per tick
  (``/root/reference/market_regime/live_market_context_accumulator.py:266-267``,
  ``/root/reference/strategies/mean_reversion_fade.py:85-90``).
* **Moments via cumsum** on row-centered data (stable in float32 even for
  BTC-scale prices), one pass for sum/mean/std.
* **Extrema via lax.reduce_window**, XLA's native sliding-window lowering.
* **Quantiles via windowed sort** (see rolling_quantile); the hot trailing
  positions have a pallas TPU count-selection kernel in
  ``ops/pallas_rolling.py`` (``rolling_quantile_tail_auto`` dispatches by
  backend; parity pinned in tests/test_pallas_rolling.py).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "shift",
    "diff",
    "rolling_sum",
    "rolling_mean",
    "rolling_std",
    "rolling_var",
    "rolling_max",
    "rolling_min",
    "rolling_quantile",
    "rolling_quantile_tail",
    "rolling_median",
    "ewm_mean",
    "ewm_mean_last",
    "rolling_mean_last",
    "rolling_std_last",
    "cummax",
    "cummin",
]


def _finite(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.isfinite(x)


def shift(x: jnp.ndarray, n: int = 1, fill_value: float = jnp.nan) -> jnp.ndarray:
    """pandas .shift(n) along the last axis (n may be negative)."""
    if n == 0:
        return x
    W = x.shape[-1]
    if abs(n) >= W:
        return jnp.full_like(x, fill_value)
    pad = jnp.full(x.shape[:-1] + (abs(n),), fill_value, dtype=x.dtype)
    if n > 0:
        return jnp.concatenate([pad, x[..., :-n]], axis=-1)
    return jnp.concatenate([x[..., -n:], pad], axis=-1)


def diff(x: jnp.ndarray, n: int = 1) -> jnp.ndarray:
    return x - shift(x, n)


def _window_sums(x: jnp.ndarray, window: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """NaN-aware (windowed sum, windowed finite-count) via cumsum."""
    m = _finite(x)
    xf = jnp.where(m, x, 0.0)
    cs = jnp.cumsum(xf, axis=-1)
    cn = jnp.cumsum(m.astype(x.dtype), axis=-1)
    cs_lag = shift(cs, window, 0.0)
    cn_lag = shift(cn, window, 0.0)
    return cs - cs_lag, cn - cn_lag


def _resolve_min_periods(window: int, min_periods: int | None) -> int:
    return window if min_periods is None else min_periods


def rolling_sum(
    x: jnp.ndarray, window: int, min_periods: int | None = None
) -> jnp.ndarray:
    wsum, cnt = _window_sums(x, window)
    mp = _resolve_min_periods(window, min_periods)
    return jnp.where(cnt >= mp, wsum, jnp.nan)


def rolling_mean(
    x: jnp.ndarray, window: int, min_periods: int | None = None
) -> jnp.ndarray:
    wsum, cnt = _window_sums(x, window)
    mp = max(_resolve_min_periods(window, min_periods), 1)
    ok = cnt >= mp
    return jnp.where(ok, wsum / jnp.where(cnt > 0, cnt, 1.0), jnp.nan)


def rolling_var(
    x: jnp.ndarray, window: int, min_periods: int | None = None, ddof: int = 1
) -> jnp.ndarray:
    # Center each row by its global nanmean first: windowed sum-of-squares on
    # centered values keeps float32 exact even when prices are O(1e4-1e5).
    m = _finite(x)
    row_cnt = jnp.sum(m, axis=-1, keepdims=True)
    row_mean = jnp.sum(jnp.where(m, x, 0.0), axis=-1, keepdims=True) / jnp.maximum(
        row_cnt, 1
    )
    xc = x - row_mean
    wsum, cnt = _window_sums(xc, window)
    wsq, _ = _window_sums(xc * xc, window)
    mp = max(_resolve_min_periods(window, min_periods), 1)
    safe_cnt = jnp.maximum(cnt, 1.0)
    var = (wsq - wsum * wsum / safe_cnt) / jnp.maximum(cnt - ddof, 1.0)
    var = jnp.maximum(var, 0.0)
    ok = (cnt >= mp) & (cnt > ddof)
    return jnp.where(ok, var, jnp.nan)


def rolling_std(
    x: jnp.ndarray, window: int, min_periods: int | None = None, ddof: int = 1
) -> jnp.ndarray:
    return jnp.sqrt(rolling_var(x, window, min_periods, ddof))


def _rolling_extremum(
    x: jnp.ndarray, window: int, min_periods: int | None, largest: bool
) -> jnp.ndarray:
    mp = max(_resolve_min_periods(window, min_periods), 1)
    neutral = -jnp.inf if largest else jnp.inf
    m = _finite(x)
    xf = jnp.where(m, x, neutral).astype(jnp.float32)
    orig_shape = xf.shape
    W = orig_shape[-1]
    flat = xf.reshape((-1, W))
    op = jax.lax.max if largest else jax.lax.min
    out = jax.lax.reduce_window(
        flat,
        jnp.float32(neutral),
        op,
        window_dimensions=(1, window),
        window_strides=(1, 1),
        padding=((0, 0), (window - 1, 0)),
    ).reshape(orig_shape)
    _, cnt = _window_sums(x, window)
    return jnp.where(cnt >= mp, out, jnp.nan)


def rolling_max(
    x: jnp.ndarray, window: int, min_periods: int | None = None
) -> jnp.ndarray:
    return _rolling_extremum(x, window, min_periods, largest=True)


def rolling_min(
    x: jnp.ndarray, window: int, min_periods: int | None = None
) -> jnp.ndarray:
    return _rolling_extremum(x, window, min_periods, largest=False)


def _windowed_view(x: jnp.ndarray, window: int) -> jnp.ndarray:
    """(..., W) -> (..., W, window): trailing window ending at each position.

    Positions before the window start are filled with NaN.
    """
    W = x.shape[-1]
    pos = jnp.arange(W)[:, None]
    off = jnp.arange(window)[None, :]
    idx = pos - (window - 1) + off
    valid = idx >= 0
    gathered = jnp.take(x, jnp.clip(idx, 0, W - 1), axis=-1)
    return jnp.where(valid, gathered, jnp.nan)


def rolling_quantile(
    x: jnp.ndarray,
    window: int,
    q: float,
    min_periods: int | None = None,
) -> jnp.ndarray:
    """pandas rolling(...).quantile(q, interpolation='linear'), NaN-aware.

    Strategy thresholds in the reference lean on shifted rolling quantiles
    (e.g. ``/root/reference/strategies/activity_burst_pump.py:123-139``,
    ``spike_hunter_v3_kucoin.py:334-346``); XLA has no native sliding
    quantile, so we sort explicit trailing windows. O(W·window·log(window))
    but embarrassingly parallel over (S, W).
    """
    mp = max(_resolve_min_periods(window, min_periods), 1)
    win = _windowed_view(x, window)  # (..., W, window)
    # NaNs sort to the end; count finite values per window for interpolation.
    cnt = jnp.sum(jnp.isfinite(win), axis=-1)
    s = jnp.sort(jnp.where(jnp.isfinite(win), win, jnp.inf), axis=-1)
    # linear interpolation at rank q*(cnt-1)
    rank = q * (cnt - 1.0)
    lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, window - 1)
    hi = jnp.clip(lo + 1, 0, window - 1)
    frac = (rank - lo.astype(x.dtype))[..., None]
    v_lo = jnp.take_along_axis(s, lo[..., None], axis=-1)
    v_hi = jnp.take_along_axis(s, jnp.minimum(hi, jnp.maximum(cnt - 1, 0))[..., None], axis=-1)
    out = (v_lo + (v_hi - v_lo) * frac)[..., 0]
    return jnp.where(cnt >= mp, out, jnp.nan)


def rolling_median(
    x: jnp.ndarray, window: int, min_periods: int | None = None
) -> jnp.ndarray:
    return rolling_quantile(x, window, 0.5, min_periods)


def rolling_quantile_tail(
    x: jnp.ndarray,
    window: int,
    q: float,
    num_out: int = 1,
    min_periods: int | None = None,
) -> jnp.ndarray:
    """Last ``num_out`` values of :func:`rolling_quantile`, (..., num_out).

    The hot tick path consumes only the trailing position(s) of a rolling
    quantile; materializing+sorting the full (S, W, window) windowed view
    was the round-1 bench's dominant kernel cost. This sorts only the
    trailing ``num_out`` windows: (S, num_out, window).
    """
    mp = max(_resolve_min_periods(window, min_periods), 1)
    W = x.shape[-1]
    num_out = min(num_out, W)
    need = min(window + num_out - 1, W)
    tail = x[..., -need:]
    pos = (need - num_out) + jnp.arange(num_out)[:, None]
    off = jnp.arange(window)[None, :]
    idx = pos - (window - 1) + off  # (num_out, window); <0 = before start
    valid = idx >= 0
    win = jnp.take(tail, jnp.clip(idx, 0, need - 1), axis=-1)
    win = jnp.where(valid, win, jnp.nan)
    cnt = jnp.sum(jnp.isfinite(win), axis=-1)
    s = jnp.sort(jnp.where(jnp.isfinite(win), win, jnp.inf), axis=-1)
    rank = q * (cnt - 1.0)
    lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, window - 1)
    hi = jnp.clip(lo + 1, 0, window - 1)
    frac = (rank - lo.astype(x.dtype))[..., None]
    v_lo = jnp.take_along_axis(s, lo[..., None], axis=-1)
    v_hi = jnp.take_along_axis(
        s, jnp.minimum(hi, jnp.maximum(cnt - 1, 0))[..., None], axis=-1
    )
    out = (v_lo + (v_hi - v_lo) * frac)[..., 0]
    return jnp.where(cnt >= mp, out, jnp.nan)


@lru_cache(maxsize=64)
def _decay_matrix(alpha: float, length: int) -> np.ndarray:
    """Lower-triangular A with A[t, s] = alpha * (1-alpha)^(t-s), s <= t."""
    d = 1.0 - alpha
    t = np.arange(length)
    expo = t[:, None] - t[None, :]
    with np.errstate(over="ignore"):
        mat = alpha * np.power(d, np.maximum(expo, 0), dtype=np.float64)
    mat = np.where(expo >= 0, mat, 0.0)
    return mat.astype(np.float32)


def ewm_mean(
    x: jnp.ndarray,
    alpha: float | None = None,
    span: float | None = None,
    min_periods: int = 0,
) -> jnp.ndarray:
    """pandas ``ewm(alpha|span, adjust=False).mean()`` as an MXU matmul.

    Exact for the leading-NaN case (the only NaN pattern the ring buffer
    produces): the recursion seeded at the first valid sample ``s0`` equals
    the uniform decay matmul plus a closed-form correction
    ``(1-alpha)^(t-s0+1) * x[s0]``.
    """
    if alpha is None:
        if span is None:
            raise ValueError("ewm_mean requires alpha or span")
        alpha = 2.0 / (span + 1.0)
    W = x.shape[-1]
    d = 1.0 - alpha
    A = jnp.asarray(_decay_matrix(float(alpha), W))

    m = _finite(x)
    xf = jnp.where(m, x, 0.0).astype(jnp.float32)
    # precision=HIGHEST: default matmul precision lowers f32 operands to
    # bf16 on TPU — fatal for EMA-of-price differences (MACD etc.).
    base = jnp.einsum(
        "ts,...s->...t",
        A,
        xf,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )

    # warm-start correction: locate first valid sample per row
    s0 = jnp.argmax(m, axis=-1)  # first True (0 if none — masked below)
    any_valid = jnp.any(m, axis=-1)
    x0 = jnp.take_along_axis(x, s0[..., None], axis=-1)[..., 0]
    t_idx = jnp.arange(W)
    rel = t_idx - s0[..., None]  # (..., W)
    corr = jnp.power(jnp.float32(d), (rel + 1).astype(jnp.float32)) * x0[..., None]
    y = base + jnp.where(rel >= 0, corr, 0.0)

    # valid only from s0 onward, with >= min_periods valid samples seen
    seen = rel + 1
    ok = (rel >= 0) & (seen >= max(min_periods, 1)) & any_valid[..., None]
    return jnp.where(ok, y, jnp.nan)


def ewm_last_state(
    x: jnp.ndarray, alpha: float
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(mean, rel, any_valid) of the ``adjust=False`` recursion at the last
    window position: the closed form behind :func:`ewm_mean_last`, exposed
    unmasked so ``ops.incremental.ewm_init`` seeds its carry from the SAME
    expressions (init-tick bit-parity is structural, not copy-maintained).
    ``rel`` is the last column's offset from the first valid sample."""
    W = x.shape[-1]
    d = 1.0 - alpha
    # weights[s] = alpha * d^(W-1-s)
    w = jnp.asarray(
        alpha * np.power(d, np.arange(W - 1, -1, -1), dtype=np.float64),
        dtype=jnp.float32,
    )
    m = _finite(x)
    xf = jnp.where(m, x, 0.0).astype(jnp.float32)
    base = jnp.einsum(
        "s,...s->...",
        w,
        xf,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    s0 = jnp.argmax(m, axis=-1)
    any_valid = jnp.any(m, axis=-1)
    x0 = jnp.take_along_axis(x, s0[..., None], axis=-1)[..., 0]
    rel = (W - 1) - s0  # position of the last column relative to first valid
    corr = jnp.power(jnp.float32(d), (rel + 1).astype(jnp.float32)) * x0
    return base + corr, rel, any_valid


def ewm_mean_last(
    x: jnp.ndarray,
    alpha: float | None = None,
    span: float | None = None,
    min_periods: int = 0,
) -> jnp.ndarray:
    """Last value of :func:`ewm_mean` in O(W) per row instead of O(W²).

    The hot per-tick path only consumes the latest EMA; this contracts
    against the decay matrix's final row (a plain weighted sum) plus the same
    closed-form warm-start correction.
    """
    if alpha is None:
        if span is None:
            raise ValueError("ewm_mean_last requires alpha or span")
        alpha = 2.0 / (span + 1.0)
    y, rel, any_valid = ewm_last_state(x, float(alpha))
    ok = any_valid & (rel + 1 >= max(min_periods, 1))
    return jnp.where(ok, y, jnp.nan)


def rolling_mean_last(
    x: jnp.ndarray, window: int, min_periods: int | None = None
) -> jnp.ndarray:
    """Last value of :func:`rolling_mean` from just the trailing slice."""
    tail = x[..., -window:]
    m = _finite(tail)
    cnt = jnp.sum(m, axis=-1)
    mp = max(_resolve_min_periods(window, min_periods), 1)
    mean = jnp.sum(jnp.where(m, tail, 0.0), axis=-1) / jnp.maximum(cnt, 1)
    return jnp.where(cnt >= mp, mean, jnp.nan)


def rolling_std_last(
    x: jnp.ndarray, window: int, min_periods: int | None = None, ddof: int = 1
) -> jnp.ndarray:
    """Last value of :func:`rolling_std` from just the trailing slice."""
    tail = x[..., -window:]
    m = _finite(tail)
    cnt = jnp.sum(m, axis=-1)
    mp = max(_resolve_min_periods(window, min_periods), 1)
    mean = jnp.sum(jnp.where(m, tail, 0.0), axis=-1) / jnp.maximum(cnt, 1)
    sq = jnp.sum(jnp.where(m, (tail - mean[..., None]) ** 2, 0.0), axis=-1)
    var = sq / jnp.maximum(cnt - ddof, 1)
    ok = (cnt >= mp) & (cnt > ddof)
    return jnp.where(ok, jnp.sqrt(jnp.maximum(var, 0.0)), jnp.nan)


def cummax(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.maximum, x, axis=-1)


def cummin(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.minimum, x, axis=-1)
