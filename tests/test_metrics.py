"""LatencyTracker: per-tick/per-stage latency histograms (SURVEY.md §5)."""

import logging

from binquant_tpu.io.metrics import LatencyTracker


def test_percentiles_and_stats():
    t = LatencyTracker()
    for v in range(1, 101):  # 1..100 ms
        t.record("tick_total", float(v))
    s = t.stats()["tick_total"]
    assert s["n"] == 100
    assert abs(s["p50_ms"] - 50.5) < 0.01
    assert abs(s["p99_ms"] - 99.01) < 0.01
    assert s["max_ms"] == 100.0
    assert abs(s["mean_ms"] - 50.5) < 0.01


def test_stage_context_manager_records():
    t = LatencyTracker()
    with t.stage("device_dispatch"):
        pass
    s = t.stats()
    assert "device_dispatch" in s and s["device_dispatch"]["n"] == 1
    assert s["device_dispatch"]["p99_ms"] >= 0.0


def test_rolling_window_bounded():
    t = LatencyTracker(window=8)
    for v in range(100):
        t.record("x", float(v))
    s = t.stats()["x"]
    assert s["n"] == 8
    assert s["max_ms"] == 99.0  # only the trailing window retained


def test_reset_clears_samples_for_phase_reuse():
    t = LatencyTracker()
    t.record("tick_total", 5.0)
    assert "tick_total" in t.stats()
    t.reset()
    assert t.stats() == {}
    # the tracker is reusable after a reset (bench phases)
    t.record("tick_total", 1.0)
    assert t.stats()["tick_total"]["n"] == 1


def test_record_mirrors_into_global_histogram():
    from binquant_tpu.obs.instruments import STAGE_LATENCY

    child = STAGE_LATENCY.labels(stage="obs_mirror_probe")
    before = child.count
    t = LatencyTracker()
    t.record("obs_mirror_probe", 3.0)
    assert child.count == before + 1
    # opt-out for synthetic micro-benchmarks
    t2 = LatencyTracker(mirror=False)
    t2.record("obs_mirror_probe", 3.0)
    assert child.count == before + 1


def test_maybe_log_cadence(caplog):
    t = LatencyTracker(log_every_s=0.0)
    t.record("tick_total", 5.0)
    with caplog.at_level(logging.INFO):
        assert t.maybe_log()
    assert any("tick latency" in r.message for r in caplog.records)
    # empty tracker logs nothing but still honors the cadence
    t2 = LatencyTracker(log_every_s=1e9)
    t2.record("x", 1.0)
    assert not t2.maybe_log()
