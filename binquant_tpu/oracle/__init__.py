"""Legacy per-symbol oracle backend (BASELINE config #1).

``backend="reference"`` runs a reference-shaped evaluation — per-symbol
pandas DataFrames, Python loops, dict carries — over the same kline stream
as the TPU batch path, emitting the same signal tuples. It is the
correctness oracle for A/B parity (SURVEY.md §7 step 8) and the benchmark
baseline the batched path is measured against.
"""

from binquant_tpu.oracle.evaluator import OracleEvaluator

__all__ = ["OracleEvaluator"]
