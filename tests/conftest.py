"""Test harness configuration.

Requests a virtual 8-device CPU mesh before jax is imported. NOTE: in the
tunneled-TPU environment the axon sitecustomize force-registers the TPU
backend regardless of JAX_PLATFORMS, so there the suite actually runs on
the real chip (clearing PALLAS_AXON_POOL_IPS in the *shell* is the only
escape hatch — too late from conftest). Elsewhere (CI, plain hosts) the
settings below take effect and provide the 8-device CPU mesh.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("ENV", "CI")
# Tier-1 compile budget: an engine on the incremental fast path compiles
# TWO wire executables (full for the cold-start/fallback resync + the
# incremental variant), which nearly doubles the suite's jit-compile time
# across the dozens of stub engines tests construct. Default the fast
# path OFF for the test lane (production default stays ON —
# binquant_tpu/config.py); the incremental coverage opts in explicitly:
# tests/test_incremental.py (step parity + pipeline gating),
# tests/test_ab_parity.py (oracle A/B with the fast path pinned on), and
# tests/test_obs.py (fallback-counter smoke).
os.environ.setdefault("BQT_INCREMENTAL", "0")
# Donated live buffers (BQT_DONATE) likewise default OFF for the tier-1
# lane: the donated wire step is a SEPARATE jit cache entry (an engine that
# crosses a depth/config boundary would compile both variants), several
# tests pin dispatch-telemetry labels to the plain step, and fixtures that
# hold pre-tick state references would be invalidated by donation.
# Production default stays ON (binquant_tpu/config.py); donated coverage
# opts in explicitly (tests/test_incremental.py::TestDonated).
os.environ.setdefault("BQT_DONATE", "0")
# Tick tracing defaults OFF for the tier-1 lane (same rationale as
# BQT_INCREMENTAL: dozens of stub engines must not each pay the span-tree
# bookkeeping). Production default stays ON (binquant_tpu/config.py);
# tracing coverage opts in explicitly by installing a Tracer(sample=1.0)
# on the engine under test (tests/test_tracing.py, tests/test_obs.py).
os.environ.setdefault("BQT_TRACE_SAMPLE", "0")
# Numeric-health digest + carry-drift meter default OFF for the tier-1
# lane: the digest is a STATIC wire-layout flag (on would change every
# engine's wire executable and break fabricated-wire fixtures), and the
# drift meter compiles one extra jit executable per audit-carrying
# engine. Production defaults stay ON (binquant_tpu/config.py); the
# numeric-health coverage opts in explicitly (tests/test_numeric_health.py).
os.environ.setdefault("BQT_NUMERIC_DIGEST", "0")
os.environ.setdefault("BQT_DRIFT_METER", "0")
# Ingest-health observatory (ISSUE 15) likewise defaults OFF for the
# tier-1 lane: the ingest digest is a STATIC wire-layout flag (on would
# change every engine's wire executable and break fabricated-wire
# fixtures) and the host monitor adds per-candle/per-batch bookkeeping
# dozens of stub engines must not each pay. Production default stays ON
# (binquant_tpu/config.py); ingest coverage opts in explicitly
# (tests/test_ingest_health.py via make_stub_engine(ingest_digest=True)).
os.environ.setdefault("BQT_INGEST_DIGEST", "0")
# Latency observatory (ISSUE 11) defaults OFF for the tier-1 lane, the
# same pattern as BQT_TRACE_SAMPLE/BQT_NUMERIC_DIGEST: dozens of stub
# engines must not each pay the freshness/phase bookkeeping, and several
# fixtures pin the pre-observatory analytics/signal-event field sets
# (freshness_ms is additive and only stamped while BQT_FRESHNESS=1).
# Production defaults stay ON (binquant_tpu/config.py); the latency
# coverage opts in explicitly (tests/test_latency.py).
os.environ.setdefault("BQT_FRESHNESS", "0")
os.environ.setdefault("BQT_HOST_PHASE", "0")
# Signal-outcome observatory (ISSUE 12) defaults OFF for the tier-1 lane,
# the same knob pattern: dozens of stub engines must not each pay the
# open-registry bookkeeping + a maturation-kernel compile, and several
# fixtures pin pre-observatory /healthz and host-carries shapes only
# additively. Production default stays ON (binquant_tpu/config.py); the
# outcome coverage opts in explicitly (tests/test_outcomes.py).
os.environ.setdefault("BQT_OUTCOMES", "0")
# Durable delivery plane (ISSUE 13) defaults OFF for the tier-1 lane, the
# same knob pattern: dozens of stub engines must not each spin per-sink
# worker tasks + a WAL file, and many fixtures pin the inline sink
# dispatch order / SINK_EMISSIONS outcomes the plane intentionally
# reshapes (enqueue-now, deliver-on-a-worker). Production default stays
# ON (binquant_tpu/config.py); delivery coverage opts in explicitly
# (tests/test_delivery.py via make_stub_engine(delivery=True)).
os.environ.setdefault("BQT_DELIVERY", "0")
# Subscription fan-out plane (ISSUE 14) defaults OFF for the tier-1 lane,
# the same knob pattern: the match kernel is a separate jit cache entry
# dozens of stub engines must not each compile, and several fixtures pin
# the pre-fanout sink dispatch / healthz shapes only additively.
# Production default stays ON (binquant_tpu/config.py); fanout coverage
# opts in explicitly (tests/test_fanout.py via make_stub_engine(fanout=True)).
os.environ.setdefault("BQT_FANOUT", "0")
# ISSUE 20 fan-out churn/boot knobs pin OFF for tier-1: no background
# compaction mid-fixture (tests drive compact() explicitly), no snapshot
# sidecar writes, no hub tail ring (the resume fixtures pin the outbox
# scan path; tail coverage opts in via fanout_overrides). Production
# defaults stay ON (binquant_tpu/config.py).
os.environ.setdefault("BQT_FANOUT_SNAPSHOT", "")
os.environ.setdefault("BQT_FANOUT_COMPACT_FRAC", "0")
os.environ.setdefault("BQT_FANOUT_RESUME_TAIL", "0")
# Unified SLO plane + delivery health collector (ISSUE 16) default OFF
# for the tier-1 lane, the same knob pattern: dozens of stub engines must
# not each pay registry/ack-side bookkeeping, and several fixtures pin
# pre-observatory /healthz and event shapes only additively. Production
# defaults stay ON (binquant_tpu/config.py); SLO coverage opts in
# explicitly (tests/test_slo.py and the chaos drills via overrides).
os.environ.setdefault("BQT_SLO", "0")
os.environ.setdefault("BQT_DELIVERY_HEALTH", "0")
# Extension-invariant chunk precompute flipped default-ON in ISSUE 18
# (the soak bed pins the governed margin contract per scenario). The
# tier-1 lane pins it OFF: the backtest parity suites drive BOTH paths
# explicitly via run_backtest(ext_invariant=...), and the serial-vs-
# vmapped bit-identity fixtures assume the per-tick gathered views.
# Ext coverage opts in explicitly (tests/test_backtest_ext.py, the soak
# drill's ext-parity stage).
os.environ.setdefault("BQT_EXT_INVARIANT", "0")
# Persistent XLA compilation cache: jit compiles dominate the tier-1
# lane's wall time (a classic wire executable alone is ~6-8 s of XLA on
# this box), and the cache key covers the optimized HLO + compile options,
# so edits that change a traced graph miss cleanly while repeat runs of
# unchanged executables deserialize in ~100 ms. Opt out (or redirect) with
# JAX_COMPILATION_CACHE_DIR=, which jax reads before this default.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "bqt-xla-cache"
    ),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def make_ohlcv(
    rng: np.random.Generator,
    n: int = 400,
    start_price: float = 100.0,
    vol: float = 0.01,
    drift: float = 0.0,
    interval_ms: int = 900_000,
    t0: int = 1_700_000_000_000,
):
    """Random-walk OHLCV arrays shaped like one symbol's window."""
    rets = rng.normal(drift, vol, size=n)
    close = start_price * np.exp(np.cumsum(rets))
    open_ = np.concatenate([[start_price], close[:-1]])
    spread = np.abs(rng.normal(0, vol / 2, size=n)) * close
    high = np.maximum(open_, close) + spread
    low = np.minimum(open_, close) - spread
    volume = np.abs(rng.normal(1000, 250, size=n))
    open_time = t0 + interval_ms * np.arange(n, dtype=np.int64)
    return {
        "open_time": open_time,
        "close_time": open_time + interval_ms - 1,
        "open": open_,
        "high": high,
        "low": low,
        "close": close,
        "volume": volume,
        "quote_asset_volume": volume * close,
        "number_of_trades": np.abs(rng.normal(500, 100, size=n)),
        "taker_buy_base_volume": volume * 0.5,
        "taker_buy_quote_volume": volume * close * 0.5,
    }


def df_from_closes(
    closes,
    interval_ms: int = 900_000,
    t0: int = 1_700_000_000_000,
    volume: float = 1000.0,
    start_price: float | None = None,
):
    """Deterministic schema-true kline DataFrame from a close series —
    the shared builder for crafted gate-test scenarios (opens chain from
    the previous close; highs/lows hug the body)."""
    import numpy as np
    import pandas as pd

    closes = np.asarray(closes, dtype=float)
    n = len(closes)
    first = start_price if start_price is not None else closes[0]
    open_ = np.concatenate([[first], closes[:-1]])
    vol = np.full(n, float(volume))
    open_time = t0 + interval_ms * np.arange(n, dtype=np.int64)
    return pd.DataFrame(
        {
            "open_time": open_time,
            "close_time": open_time + interval_ms - 1,
            "open": open_,
            "high": np.maximum(open_, closes) * 1.0005,
            "low": np.minimum(open_, closes) * 0.9995,
            "close": closes,
            "volume": vol,
            "quote_asset_volume": closes * vol,
            "number_of_trades": np.full(n, 400.0),
            "taker_buy_base_volume": vol / 2,
            "taker_buy_quote_volume": closes * vol / 2,
        }
    )


@pytest.fixture
def ohlcv(rng):
    return make_ohlcv(rng)
