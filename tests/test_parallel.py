"""Multi-chip sharding tests: sharded tick_step == unsharded tick_step.

SURVEY §2.9: the framework's parallelism is data parallelism over the
symbol axis (NamedSharding over a 1-D ``symbols`` mesh). These tests pin
that the sharded step produces bit-for-bit (float-tolerant) identical
outputs and that the driver-facing ``dryrun_multichip`` entry succeeds.

On plain hosts/CI the conftest provisions an 8-device virtual CPU mesh
in-process. On the tunneled-TPU host the axon sitecustomize forces the
1-chip TPU backend, so the in-process tests skip and the subprocess
tests (which set the escape-hatch env before jax import) carry the
coverage.
"""

import subprocess
import sys

import jax
import pytest

import __graft_entry__ as graft

multi = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh)"
)


@multi
def test_sharded_tick_matches_unsharded():
    graft._parity_check(8)


@multi
def test_dryrun_multichip_inprocess():
    graft._dryrun_inprocess(8)


def test_mesh_shardings_place_symbol_axis():
    from binquant_tpu.parallel import make_mesh, shard_engine_state

    n = min(len(jax.devices()), 8)
    mesh = make_mesh(jax.devices()[:n])
    state, _, _ = graft._example_inputs(num_symbols=n * 2, window=64)
    sharded = shard_engine_state(state, mesh)
    spec = sharded.buf15.values.sharding.spec
    assert spec[0] == "symbols"
    # carry scalars replicated
    assert sharded.regime_carry.market_regime.sharding.is_fully_replicated


def test_dryrun_multichip_driver_entry():
    """The driver calls dryrun_multichip(n) in-process with whatever
    backend is active; it must succeed regardless (subprocess fallback)."""
    graft.dryrun_multichip(8)


@pytest.mark.slow
def test_parity_subprocess_eight_cpu_devices():
    """Full sharded-vs-unsharded parity under a forced 8-CPU mesh, env set
    before jax import (works on the tunneled-TPU host too)."""
    proc = subprocess.run(
        [sys.executable, "-c", "import __graft_entry__ as g; g._parity_check(8)"],
        env=graft._subprocess_env(8),
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "parity ok" in proc.stdout
