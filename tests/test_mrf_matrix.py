"""MeanReversionFade gate matrix (reference test_mean_reversion_fade.py).

Short entry, ATR-derived stop-loss, candle-color and band rejects, and the
ATR-spike veto — each scenario's entry conditions are confirmed with the
pandas oracle so the crafted data provably reaches the gate under test.
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd

from binquant_tpu.enums import Direction
from binquant_tpu.strategies import compute_feature_pack
from binquant_tpu.strategies.mean_reversion_fade import mean_reversion_fade
from tests.conftest import make_ohlcv
from tests.test_strategies_live import S_CAP, WINDOW, craft_mrf_long, fill_buffer


def craft_mrf_short(rng, n=WINDOW):
    """Monotonic rise then a red shooting star at the upper band."""
    d = make_ohlcv(rng, n=n, start_price=100, vol=0.004, drift=0.004)
    df = pd.DataFrame(d)
    i = len(df) - 1
    prev_close = df["close"].iloc[i - 1]
    o = prev_close * 1.03
    c = o * 0.996  # red
    df.loc[df.index[i], "open"] = o
    df.loc[df.index[i], "close"] = c
    df.loc[df.index[i], "high"] = o * 1.002
    df.loc[df.index[i], "low"] = c * 0.999
    df.loc[df.index[i], "volume"] = df["volume"].iloc[-21:-1].mean() * 2
    return df


def oracle(df):
    """(rsi_wilder, bb_low, bb_high, atr, atr_ma) at the last bar."""
    closes = df["close"].astype(float)
    delta = closes.diff()
    ag = delta.clip(lower=0).ewm(alpha=1 / 14, min_periods=14, adjust=False).mean()
    al = (-delta.clip(upper=0)).ewm(alpha=1 / 14, min_periods=14, adjust=False).mean()
    rsi = float((100 * ag / (ag + al)).where((ag + al) != 0, 50.0).iloc[-1])
    mid = closes.rolling(20).mean()
    std = closes.rolling(20).std(ddof=0)
    tail = df.tail(35)
    pc = tail["close"].shift(1)
    tr = pd.concat(
        [
            tail["high"] - tail["low"],
            (tail["high"] - pc).abs(),
            (tail["low"] - pc).abs(),
        ],
        axis=1,
    ).max(axis=1).iloc[1:]
    atr_series = tr.rolling(14).mean()
    return (
        rsi,
        float((mid - 2 * std).iloc[-1]),
        float((mid + 2 * std).iloc[-1]),
        float(atr_series.iloc[-1]),
        float(atr_series.rolling(20).mean().iloc[-1]),
    )


def run_mrf(df, futures=True, carry=None):
    buf = fill_buffer({0: df})
    pack = compute_feature_pack(buf)
    if carry is None:
        carry = jnp.full((S_CAP,), -1, dtype=jnp.int32)
    return mean_reversion_fade(pack, jnp.asarray(futures), carry)


class TestShortEntry:
    def test_short_fires_with_atr_stop(self):
        rng = np.random.default_rng(61)
        df = craft_mrf_short(rng)
        rsi, _, bb_high, atr, _ = oracle(df)
        c, o = float(df["close"].iloc[-1]), float(df["open"].iloc[-1])
        # the crafted data must provably reach the short gate
        assert rsi >= 75.0 and c >= bb_high and c < o
        out, carry2 = run_mrf(df)
        assert bool(out.trigger[0])
        assert int(out.direction[0]) == int(Direction.SHORT)
        assert bool(out.autotrade[0])
        # score = 1 + overbought depth
        np.testing.assert_allclose(
            float(out.score[0]),
            round(1.0 + max(0.0, (rsi - 75.0) / 25.0), 4),
            rtol=1e-3,
        )
        # ATR-sized stop: 2*atr/close*100, clamped [0, 101], rounded
        np.testing.assert_allclose(
            float(out.stop_loss_pct[0]),
            round(min(2.0 * atr / c * 100.0, 101.0), 4),
            rtol=1e-3,
        )
        # same candle again -> deduped
        out2, _ = run_mrf(df, carry=carry2)
        assert not bool(out2.trigger[0])

    def test_green_candle_rejects_short(self):
        rng = np.random.default_rng(61)
        df = craft_mrf_short(rng)
        i = df.index[-1]
        df.loc[i, "close"] = float(df["open"].iloc[-1]) * 1.001  # green
        df.loc[i, "high"] = float(df["close"].iloc[-1]) * 1.001
        # candle color must be the ONLY failing gate
        rsi, _, bb_high, _, _ = oracle(df)
        assert rsi >= 75.0 and float(df["close"].iloc[-1]) >= bb_high
        assert not bool(run_mrf(df)[0].trigger[0])


class TestLongRejects:
    def test_red_candle_rejects_long(self):
        rng = np.random.default_rng(53)
        df = craft_mrf_long(rng)
        i = df.index[-1]
        df.loc[i, "close"] = float(df["open"].iloc[-1]) * 0.999  # red
        df.loc[i, "low"] = float(df["close"].iloc[-1]) * 0.999
        # candle color must be the ONLY failing gate
        rsi, bb_low, _, _, _ = oracle(df)
        assert rsi <= 25.0 and float(df["close"].iloc[-1]) <= bb_low
        assert not bool(run_mrf(df)[0].trigger[0])

    def test_price_above_lower_band_rejects_long(self):
        rng = np.random.default_rng(53)
        df = craft_mrf_long(rng)
        # lift the hammer back inside the bands (same shape, higher close)
        i = df.index[-1]
        prev_close = float(df["close"].iloc[-2])
        df.loc[i, "open"] = prev_close * 0.999
        df.loc[i, "close"] = prev_close * 1.002
        df.loc[i, "high"] = prev_close * 1.003
        df.loc[i, "low"] = prev_close * 0.998
        _, bb_low, _, _, _ = oracle(df)
        assert float(df["close"].iloc[-1]) > bb_low
        assert not bool(run_mrf(df)[0].trigger[0])

    def test_atr_spike_vetoes(self):
        rng = np.random.default_rng(53)
        df = craft_mrf_long(rng)
        # blow out the trailing 4 bars' ranges: ATR(14) spikes while its
        # 20-bar MA lags -> atr >= 2*atr_ma vetoes the (still valid) setup
        for k in range(2, 6):
            i = df.index[-k]
            c = float(df["close"].iloc[-k])
            df.loc[i, "high"] = c * 1.30
            df.loc[i, "low"] = c * 0.70
        rsi, bb_low, _, atr, atr_ma = oracle(df)
        c = float(df["close"].iloc[-1])
        assert rsi <= 25.0 and c <= bb_low  # setup still present
        assert atr >= 2.0 * atr_ma  # and the veto provably engaged
        assert not bool(run_mrf(df)[0].trigger[0])

    def test_spot_market_never_emits(self):
        rng = np.random.default_rng(53)
        df = craft_mrf_long(rng)
        rsi, bb_low, _, _, _ = oracle(df)
        assert rsi <= 25.0 and float(df["close"].iloc[-1]) <= bb_low
        assert bool(run_mrf(df, futures=True)[0].trigger[0])
        assert not bool(run_mrf(df, futures=False)[0].trigger[0])
