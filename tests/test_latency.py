"""Latency observatory (ISSUE 11): freshness-histogram correctness on a
fake clock, SLO-breach force-emit, chunk occupancy summing to chunk wall,
the shared serial/scanned phase taxonomy, chunk-span waterfalls, and the
timeline-export golden."""

from __future__ import annotations

import asyncio
import json
import sys
from pathlib import Path

import pytest

from binquant_tpu.obs.events import EventLog, set_event_log
from binquant_tpu.obs.latency import (
    PHASES,
    FreshnessTracker,
    PhaseAccountant,
)
from binquant_tpu.obs.registry import REGISTRY
from binquant_tpu.obs.tracing import Tracer

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import latency_report  # noqa: E402
import timeline_export  # noqa: E402
import trace_report  # noqa: E402

# serial shapes shared with tests/test_obs.py / test_tracing.py (compile
# cache hit); scanned shapes shared with tests/test_scan_replay.py
CAP, WIN = 16, 130
SCAN_CAP, SCAN_WIN = 32, 120


@pytest.fixture
def event_log(tmp_path):
    path = tmp_path / "events.jsonl"
    log = EventLog(path)
    set_event_log(log)
    try:
        yield path
    finally:
        log.close()
        set_event_log(None)


def _read_events(path) -> list[dict]:
    if not Path(path).exists():  # nothing emitted yet (lazy file sink)
        return []
    return [json.loads(ln) for ln in Path(path).read_text().splitlines()]


def _hist_child(name: str, **labels):
    fam = REGISTRY.get(name)
    assert fam is not None, name
    return fam.labels(**labels)


def _counter_value(name: str) -> float:
    fam = REGISTRY.get(name)
    return 0.0 if fam is None else fam._solo().value


# ---------------------------------------------------------------------------
# unit: freshness tracker on a fake clock
# ---------------------------------------------------------------------------


def test_freshness_histograms_fake_clock(event_log):
    """Hand-fed stamps land in the right stage/sink children with exact
    sums and counts — the histogram math checked against a fake clock's
    known values (no wall time involved)."""
    tracker = FreshnessTracker(enabled=True, slo_ms=0.0)
    stage = _hist_child("bqt_freshness_ms", stage="close_to_emit")
    ack_stage = _hist_child("bqt_freshness_ms", stage="close_to_sink_ack")
    sink = _hist_child("bqt_sink_delivery_ms", sink="telegram")
    sum0, count0 = stage.sum, stage.count
    ack_sum0 = ack_stage.sum
    sink_count0 = sink.count

    worst = tracker.observe_signal(
        "abp", "BTCUSDT", 40.0,
        sink_ack_ms={"telegram": 55.0, "analytics": 45.0},
    )
    assert worst == 55.0  # close->sink-ack = the worst sink
    worst = tracker.observe_signal(
        "abp", "ETHUSDT", 10.0, sink_ack_ms={"telegram": 5.0}
    )
    assert worst == 10.0  # never below close->emit itself

    assert stage.count == count0 + 2
    assert stage.sum == pytest.approx(sum0 + 50.0)
    assert ack_stage.sum == pytest.approx(ack_sum0 + 65.0)
    assert sink.count == sink_count0 + 2
    assert tracker.snapshot()["signals"] == 2
    assert tracker.snapshot()["last_ms"]["close_to_emit"] == 10.0
    # no SLO configured: nothing breached, nothing emitted
    assert tracker.breaches == 0
    assert all(
        e["event"] != "freshness_slo_breach" for e in _read_events(event_log)
    )

    # disabled tracker is a no-op (the tier-1 default)
    off = FreshnessTracker(enabled=False, slo_ms=1.0)
    assert off.observe_signal("abp", "X", 1e9) is None
    assert off.signals == 0 and stage.count == count0 + 2


def test_freshness_slo_breach_force_emits(event_log):
    """A signal whose worst sink ack crosses the SLO force-emits a
    freshness_slo_breach with the phase breakdown + engine snapshot."""
    tracker = FreshnessTracker(enabled=True, slo_ms=100.0)
    before = _counter_value("bqt_freshness_slo_breaches_total")
    tracker.observe_signal(
        "lsp", "BTCUSDT", 80.0,
        sink_ack_ms={"autotrade": 150.0},
        tick_ms=123000,
        trace_id="cafe",
        phases={"drive": "scanned", "wall_ms": 200.0},
        snapshot_fn=lambda: {"queue_depth": 3},
    )
    tracker.observe_signal("lsp", "ETHUSDT", 20.0)  # under SLO: no event
    assert tracker.breaches == 1
    assert _counter_value("bqt_freshness_slo_breaches_total") == before + 1
    (breach,) = [
        e for e in _read_events(event_log)
        if e["event"] == "freshness_slo_breach"
    ]
    assert breach["close_to_sink_ack_ms"] == 150.0
    assert breach["slo_ms"] == 100.0
    assert breach["sink_ack_ms"] == {"autotrade": 150.0}
    assert breach["host_phases"]["drive"] == "scanned"
    assert breach["engine"] == {"queue_depth": 3}
    assert breach["trace_id"] == "cafe"


# ---------------------------------------------------------------------------
# unit: phase accountant occupancy identity
# ---------------------------------------------------------------------------


def test_occupancy_sums_to_chunk_wall_exactly():
    acc = PhaseAccountant(enabled=True)
    acc.begin_chunk("scanned")
    acc.record("scanned", "plan", 10.0)
    acc.record("scanned", "stack", 5.0)
    acc.record("scanned", "dispatch", 40.0)
    acc.record("scanned", "device_wait", 30.0)
    acc.record("scanned", "decode", 8.0)
    # mid-chunk readers (an SLO breach during finalize) see the OPEN
    # chunk's split-so-far, not the previous chunk's
    mid = acc.open_split("scanned")
    assert mid["drive"] == "scanned" and mid["dispatch"] == 40.0
    acc.record("scanned", "emit", 2.0)
    occ = acc.note_chunk("scanned", 100.0, 16)
    assert acc.open_split("scanned") is None  # chunk closed
    assert occ["device_wait_ms"] == 30.0
    assert occ["host_ms"] == 65.0
    assert occ["dead_gap_ms"] == 5.0
    # the identity the acceptance criterion pins: wall == device + host +
    # dead gap, and the attribution percentage reads off the same split
    assert (
        occ["device_wait_ms"] + occ["host_ms"] + occ["dead_gap_ms"]
        == occ["wall_ms"]
    )
    assert occ["attributed_pct"] == 95.0
    snap = acc.snapshot()
    assert snap["occupancy"]["scanned"]["ticks"] == 16
    assert set(snap["phase_ms"]["scanned"]) == set(PHASES)
    # a second chunk diffs against its own marks, not the totals
    acc.begin_chunk("scanned")
    acc.record("scanned", "plan", 1.0)
    occ2 = acc.note_chunk("scanned", 2.0, 4)
    assert occ2["host_ms"] == 1.0 and occ2["dead_gap_ms"] == 1.0
    # disabled accountant records nothing and notes nothing
    off = PhaseAccountant(enabled=False)
    off.begin_chunk("serial")
    off.record("serial", "plan", 1.0)
    assert off.note_chunk("serial", 1.0, 1) is None
    assert off.open_split("serial") is None
    assert off.snapshot()["phase_ms"] == {}


# ---------------------------------------------------------------------------
# end-to-end: replayed burst with the observatory on
# ---------------------------------------------------------------------------


def _drive_serial(engine, path) -> list:
    from binquant_tpu.io.replay import load_klines_by_tick

    by_tick = load_klines_by_tick(path)

    async def go() -> list:
        fired = []
        for bucket in sorted(by_tick):
            for k in sorted(by_tick[bucket], key=lambda k: k["open_time"]):
                engine.ingest(k)
            fired.extend(
                await engine.process_tick(now_ms=(bucket + 1) * 900 * 1000)
            )
        fired.extend(await engine.flush_pending())
        return fired

    return asyncio.run(go())


def test_replay_freshness_end_to_end(tmp_path, event_log):
    """Every emitted signal carries a finite close→emit stamp into the
    analytics payload, metadata, and the signal event; the engine's
    freshness snapshot counts them; /healthz exposes the section."""
    from binquant_tpu.io.replay import generate_burst_replay, make_stub_engine

    path = tmp_path / "burst.jsonl"
    generate_burst_replay(path, n_symbols=8, n_ticks=108)
    engine = make_stub_engine(
        capacity=CAP, window=WIN, pipeline_depth=0,
        freshness=True, host_phase=True,
    )
    fired = _drive_serial(engine, path)
    assert fired, "burst fixture must fire signals"
    for signal in fired:
        assert signal.freshness_ms is not None
        assert signal.freshness_ms == signal.analytics["freshness_ms"]
        assert signal.freshness_ms == signal.value.metadata["freshness_ms"]
        # the evaluated bar closed before the tick dispatched: staleness
        # is bounded below by the logical close→tick gap (>= 0 here)
        assert signal.freshness_ms >= 0
    signal_events = [
        e for e in _read_events(event_log) if e["event"] == "signal"
    ]
    assert signal_events and all(
        e.get("freshness_ms") is not None for e in signal_events
    )
    fresh = engine.freshness.snapshot()
    assert fresh["signals"] == len(fired)
    assert fresh["slo_breaches"] == 0
    # every stage observed at least once on the serial drive
    for stage in (
        "close_to_dispatch", "ingest_to_dispatch", "dispatch_to_fetch",
        "close_to_emit", "close_to_sink_ack",
    ):
        assert stage in fresh["last_ms"], stage
    health = engine.health_snapshot()
    assert health["latency"]["freshness"]["signals"] == len(fired)
    assert health["latency"]["host_phase"]["enabled"] is True


def test_replay_slo_breach_forced(tmp_path, event_log):
    """slo_ms below any real end-to-end latency: every signal breaches,
    each force-emitting with an engine snapshot attached."""
    from binquant_tpu.io.replay import generate_burst_replay, make_stub_engine

    path = tmp_path / "burst.jsonl"
    generate_burst_replay(path, n_symbols=8, n_ticks=108)
    engine = make_stub_engine(
        capacity=CAP, window=WIN, pipeline_depth=0,
        freshness=True, host_phase=True, freshness_slo_ms=1e-6,
    )
    fired = _drive_serial(engine, path)
    assert fired
    breaches = [
        e for e in _read_events(event_log)
        if e["event"] == "freshness_slo_breach"
    ]
    assert len(breaches) == len(fired) == engine.freshness.breaches
    for b in breaches:
        assert b["close_to_sink_ack_ms"] >= b["close_to_emit_ms"] >= 0
        assert set(b["sink_ack_ms"]) == {"analytics", "telegram", "autotrade"}
        assert "ticks_processed" in b["engine"]
        # the PRODUCING chunk's split-so-far rides the breach (its tick's
        # serial chunk is still open while finalize emits)
        assert b["host_phases"]["drive"] == "serial"
    assert (
        engine._flight_snapshot()["freshness_slo_breaches"] == len(fired)
    )


def test_scanned_vs_serial_phase_taxonomy_and_occupancy(tmp_path, event_log):
    """One scanned drive (whose cold-start tick re-enters the serial
    path) reports BOTH drives under the SAME phase taxonomy, and each
    chunk's occupancy split sums to its wall clock with ≥90% attributed
    to named phases."""
    from binquant_tpu.io.replay import generate_replay_file, run_replay

    path = tmp_path / "scan.jsonl"
    generate_replay_file(path, n_symbols=8, n_ticks=24)
    stats = run_replay(
        path, capacity=SCAN_CAP, window=SCAN_WIN, scanned=True,
        incremental=True, scan_chunk=8,
        freshness=True, host_phase=True,
    )
    assert stats["scan_chunks"] >= 1
    host_phase = stats["latency"]["host_phase"]
    phase_ms = host_phase["phase_ms"]
    assert set(phase_ms) == {"serial", "scanned"}
    # the acceptance pin: both drives report the identical taxonomy
    assert set(phase_ms["serial"]) == set(phase_ms["scanned"]) == set(PHASES)
    for drive, occ in host_phase["occupancy"].items():
        total = (
            occ["device_wait_ms"] + occ["host_ms"] + occ["dead_gap_ms"]
        )
        assert total == pytest.approx(occ["wall_ms"], abs=0.01), drive
        assert occ["attributed_pct"] >= 90.0, (drive, occ)
    assert host_phase["occupancy"]["scanned"]["ticks"] == stats[
        "scanned_ticks"
    ]
    # the chunk-level dispatch→wire-fetch freshness stamp landed
    assert "dispatch_to_fetch" in stats["latency"]["freshness"]["last_ms"]
    # the run's summary event rode the log for offline reporting
    summaries = [
        e for e in _read_events(event_log) if e["event"] == "latency_summary"
    ]
    assert summaries and summaries[-1]["host_phase"]["occupancy"]


def test_chunk_trace_carries_phase_children(tmp_path, event_log):
    """The scanned chunk's trace is a phase waterfall (stack / dispatch /
    device_wait children + plan/finalize root spans), not one opaque
    bar — and trace_report renders it."""
    from binquant_tpu.io.replay import (
        generate_replay_file,
        load_klines_by_tick,
        make_stub_engine,
    )

    path = tmp_path / "scan.jsonl"
    generate_replay_file(path, n_symbols=8, n_ticks=24)
    engine = make_stub_engine(
        capacity=SCAN_CAP, window=SCAN_WIN, incremental=True,
        scan_chunk=8, freshness=True, host_phase=True,
    )
    engine.tracer = Tracer(sample=1.0, slow_ms=1e9, ring=64)
    by_tick = load_klines_by_tick(path)
    seq = [
        (
            (bucket + 1) * 900 * 1000,
            sorted(by_tick[bucket], key=lambda k: k["open_time"]),
        )
        for bucket in sorted(by_tick)
    ]
    asyncio.run(engine.process_ticks_scanned(seq))
    asyncio.run(engine.flush_pending())
    chunk_traces = [
        e
        for e in _read_events(event_log)
        if e["event"] == "trace" and e.get("path") == "scanned"
    ]
    assert chunk_traces, "at least one scan chunk must trace"
    tree = chunk_traces[0]["spans"]
    top = {c["name"]: c for c in tree["children"]}
    assert {"plan", "scan_chunk", "finalize"} <= set(top)
    kids = {c["name"] for c in top["scan_chunk"]["children"]}
    assert {"stack", "dispatch", "device_wait"} <= kids
    assert top["plan"]["attrs"]["accumulated"] is True
    assert top["finalize"]["attrs"]["ticks"] == top["scan_chunk"]["attrs"][
        "ticks"
    ]
    # spans carry the timeline exporter's placement offsets
    assert "t0" in top["scan_chunk"]
    assert trace_report.main([str(event_log), "--slowest", "2"]) == 0


# ---------------------------------------------------------------------------
# goldens: chunk waterfall + timeline export
# ---------------------------------------------------------------------------

_CHUNK_EVENT = {
    "event": "trace",
    "trace_id": "00c0ffee00c0ffee",
    "tick_seq": 7,
    "busy_ms": 100.0,
    "wall_ms": 130.0,
    "status": "ok",
    "path": "scanned",
    "ts": 1700000000.13,
    "spans": {
        "name": "tick",
        "span_id": "aaaaaaaa",
        "ms": 130.0,
        "t0": 0.0,
        "status": "ok",
        "children": [
            {
                "name": "plan",
                "span_id": "bbbbbbbb",
                "ms": 8.0,
                "t0": -8.0,
                "status": "ok",
                "attrs": {"accumulated": True, "ticks": 16},
            },
            {
                "name": "scan_chunk",
                "span_id": "cccccccc",
                "ms": 90.0,
                "t0": 0.0,
                "status": "ok",
                "attrs": {"ticks": 16, "padded": 16, "depth": 1},
                "children": [
                    {
                        "name": "stack",
                        "span_id": "dddddddd",
                        "ms": 5.0,
                        "t0": 0.0,
                        "status": "ok",
                    },
                    {
                        "name": "dispatch",
                        "span_id": "eeeeeeee",
                        "ms": 60.0,
                        "t0": 5.0,
                        "status": "ok",
                    },
                    {
                        "name": "device_wait",
                        "span_id": "ffffffff",
                        "ms": 25.0,
                        "t0": 65.0,
                        "status": "ok",
                    },
                ],
            },
            {
                "name": "finalize",
                "span_id": "99999999",
                "ms": 2.0,
                "t0": 90.0,
                "status": "ok",
                "attrs": {"ticks": 16},
            },
        ],
    },
}

_CHUNK_RENDERED = """\
trace 00c0ffee00c0ffee  tick 7  status ok  busy 100.0ms  wall 130.0ms  path scanned
  plan                         8.000ms   8.0%  accumulated=True ticks=16
  scan_chunk                  90.000ms  90.0%  ticks=16 padded=16 depth=1
    stack                        5.000ms   5.0%
    dispatch                    60.000ms  60.0%
    device_wait                 25.000ms  25.0%
  finalize                     2.000ms   2.0%  ticks=16"""


def test_trace_report_chunk_waterfall_golden():
    assert trace_report.render_trace(_CHUNK_EVENT) == _CHUNK_RENDERED


def test_timeline_export_golden(tmp_path):
    doc = timeline_export.export([_CHUNK_EVENT])
    events = doc["traceEvents"]
    # lane metadata first: one process + two named lanes
    assert [e["name"] for e in events[:3]] == [
        "process_name", "thread_name", "thread_name",
    ]
    slices = {e["name"]: e for e in events[3:]}
    root_start_us = 1700000000.13 * 1e6 - 130.0 * 1000.0
    assert slices["tick 7"]["tid"] == timeline_export.TID_HOST
    assert slices["tick 7"]["ts"] == pytest.approx(root_start_us, abs=0.2)
    assert slices["tick 7"]["dur"] == pytest.approx(130000.0)
    # host lane: plan/stack/finalize; device lane: dispatch/device_wait
    for name in ("plan", "scan_chunk", "stack", "finalize"):
        assert slices[name]["tid"] == timeline_export.TID_HOST, name
    for name in ("dispatch", "device_wait"):
        assert slices[name]["tid"] == timeline_export.TID_DEVICE, name
    # t0 placement: device_wait starts 65ms after the root
    assert slices["device_wait"]["ts"] == pytest.approx(
        root_start_us + 65000.0, abs=0.2
    )
    assert slices["device_wait"]["dur"] == pytest.approx(25000.0)
    # the accumulated plan span sits BEFORE the chunk anchor
    assert slices["plan"]["ts"] == pytest.approx(
        root_start_us - 8000.0, abs=0.2
    )
    assert slices["scan_chunk"]["args"]["trace_id"] == "00c0ffee00c0ffee"

    # CLI round trip: file in, chrome-trace json out
    log = tmp_path / "ev.jsonl"
    log.write_text(
        json.dumps({"event": "signal"}) + "\n" + json.dumps(_CHUNK_EVENT)
        + "\n"
    )
    out = tmp_path / "timeline.json"
    assert timeline_export.main([str(log), "--out", str(out)]) == 0
    parsed = json.loads(out.read_text())
    assert parsed["displayTimeUnit"] == "ms"
    assert len(parsed["traceEvents"]) == len(events)
    # filters + empty-log failure mode
    assert timeline_export.main([str(log), "--tick", "7"]) == 0
    assert timeline_export.main([str(log), "--tick", "99"]) == 1


def test_latency_report_renders_summary(tmp_path, capsys):
    log = tmp_path / "ev.jsonl"
    records = [
        {
            "event": "latency_summary",
            "freshness": {
                "signals": 3,
                "slo_ms": 250.0,
                "slo_breaches": 1,
                "last_ms": {"close_to_emit": 12.5},
            },
            "host_phase": {
                "phase_ms": {
                    "scanned": {"plan": {"total_ms": 10.0, "count": 2}}
                },
                "occupancy": {
                    "scanned": {
                        "wall_ms": 100.0, "device_wait_ms": 40.0,
                        "host_ms": 55.0, "dead_gap_ms": 5.0,
                        "attributed_pct": 95.0, "chunks": 2, "ticks": 16,
                    }
                },
            },
        },
        {"event": "signal", "strategy": "abp", "freshness_ms": 12.5},
        {"event": "signal", "strategy": "abp", "freshness_ms": 20.0},
        {
            "event": "freshness_slo_breach",
            "strategy": "abp",
            "symbol": "BTCUSDT",
            "close_to_sink_ack_ms": 300.0,
            "slo_ms": 250.0,
            "tick_ms": 1,
        },
    ]
    log.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    assert latency_report.main([str(log)]) == 0
    out = capsys.readouterr().out
    assert "freshness" in out
    assert "occupancy" in out
    assert "dead_gap=5.0ms" in out
    assert "SLO breaches (1)" in out
    assert "abp" in out
