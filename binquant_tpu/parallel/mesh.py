"""Device mesh + shardings for the symbol axis.

Strategy (scaling-book recipe): pick a 1-D mesh over all devices, annotate
every ``(S, ...)`` array with ``P("symbols", ...)`` and every scalar/carry
with replication, then let XLA insert collectives. The only cross-symbol
communication in the whole tick is the market-context reduction
(advancers/averages — a handful of psums over ICI per tick); strategies,
indicators, and the ring-buffer update are element-wise over S and run
fully parallel.

Capacity S must be a multiple of the mesh size (the registry pads — S is a
static config knob, BQT_MAX_SYMBOLS).

ASSEMBLY — pod-shaped everywhere. Every placement routes through
``jax.make_array_from_single_device_arrays``: the host slices each leaf
along the symbol axis with the sharding's own device→index map and ships
each shard's bytes straight to the device that owns it, then stitches the
global ``jax.Array`` from those single-device pieces. On one host that is
exactly the multi-host construction with *all* shards addressable, so the
CPU virtual mesh (``--xla_force_host_platform_device_count``, the dryrun
lane) validates the identical code path a real pod runs per process —
no full-array ``device_put`` + GSPMD redistribution anywhere, including
the per-tick ``HostInputs`` hot path (``shard_host_inputs`` and the
pipeline's ``_place_symbol_array``).

``make_mesh`` still fails fast under multi-process JAX: the assembly is
process-local by construction, but the *control plane* around it (one
registry claiming rows, one ingest batcher, one outbox cursor) has not
been split per process yet. A pod additionally needs each process to run
ingest for only its own row range (``shard_bounds``/``shard_of_row`` are
the routing primitives) and the checkpoint restore to re-slice per
process (``io/checkpoint.py`` sharded archives).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from binquant_tpu.engine.buffer import MarketBuffer
from binquant_tpu.engine.step import EngineState, HostInputs
from binquant_tpu.regime.context import RegimeCarry


def make_mesh(devices: list | None = None, axis: str = "symbols") -> Mesh:
    if jax.process_count() > 1:
        raise NotImplementedError(
            "binquant_tpu's mesh mode is single-host: the per-shard "
            "assembly (make_array_from_single_device_arrays) is already "
            "pod-shaped, but the registry/ingest/outbox control plane is "
            "one process (see module docstring for the per-process split "
            "a pod would need)"
        )
    devs = np.array(devices if devices is not None else jax.devices())
    return Mesh(devs, axis_names=(axis,))


def symbol_sharding(mesh: Mesh, ndim: int = 1, axis: str = "symbols") -> NamedSharding:
    """NamedSharding splitting the leading (symbol) axis."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def _replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_bounds(capacity: int, n_shards: int) -> list[tuple[int, int]]:
    """Contiguous ``[lo, hi)`` row range each shard owns.

    NamedSharding over a 1-D mesh splits the leading axis into equal
    contiguous blocks in mesh-device order — shard ``k`` owns rows
    ``[k·S/N, (k+1)·S/N)``. This is the single source of truth the ingest
    router, the sharded checkpoint archives, and the per-shard outbox
    partitions all derive from.
    """
    if capacity % n_shards:
        raise ValueError(
            f"capacity {capacity} not divisible by {n_shards} shards"
        )
    block = capacity // n_shards
    return [(k * block, (k + 1) * block) for k in range(n_shards)]


def shard_of_row(row: int, capacity: int, n_shards: int) -> int:
    """Which shard owns registry row ``row`` (see :func:`shard_bounds`)."""
    block = capacity // n_shards
    if row < 0 or row >= capacity:
        raise ValueError(f"row {row} outside capacity {capacity}")
    return row // block


def assemble_sharded(mesh: Mesh, host, sharding: NamedSharding | None = None):
    """Build a global ``jax.Array`` from per-shard host slices.

    ``host`` is a full host-side array (numpy or convertible); each
    device's slice is taken via the sharding's device→index map and put
    on that device alone, then the global array is stitched with
    ``jax.make_array_from_single_device_arrays``. No full-array
    ``device_put`` happens: shard k's bytes travel only to device k.
    On a multi-host pod the identical call works per process — the index
    map yields only addressable devices, so each process slices just the
    rows it owns.
    """
    host = np.asarray(host)
    if sharding is None:
        sharding = symbol_sharding(mesh, max(host.ndim, 1))
    if host.ndim == 0:
        sharding = _replicated(mesh)
    dmap = sharding.addressable_devices_indices_map(host.shape)
    leaves = [jax.device_put(host[idx], d) for d, idx in dmap.items()]
    return jax.make_array_from_single_device_arrays(
        host.shape, sharding, leaves
    )


def assemble_from_slices(mesh: Mesh, slices: list, sharding: NamedSharding):
    """Pod-primitive twin of :func:`assemble_sharded` for callers that
    already hold per-shard slices (ingest routing, sharded checkpoint
    restore): ``slices[k]`` goes to mesh device ``k`` verbatim — the host
    never materializes the concatenated array at all."""
    devs = list(mesh.devices.flat)
    if len(slices) != len(devs):
        raise ValueError(
            f"{len(slices)} slices for {len(devs)} mesh devices"
        )
    lead = sum(np.asarray(s).shape[0] for s in slices)
    trailing = np.asarray(slices[0]).shape[1:]
    leaves = [jax.device_put(np.asarray(s), d) for s, d in zip(slices, devs)]
    return jax.make_array_from_single_device_arrays(
        (lead, *trailing), sharding, leaves
    )


def _put(mesh: Mesh, x, sharding: NamedSharding):
    """Place one leaf through the per-shard assembly, skipping leaves that
    already carry the target sharding (idempotent re-shard on restore)."""
    if isinstance(x, jax.Array) and not isinstance(x, jax.core.Tracer):
        if getattr(x, "sharding", None) == sharding:
            return x
    return assemble_sharded(mesh, x, sharding)


def _shard_buffer(buf: MarketBuffer, mesh: Mesh) -> MarketBuffer:
    s2 = symbol_sharding(mesh, 2)
    s3 = symbol_sharding(mesh, 3)
    s1 = symbol_sharding(mesh, 1)
    return MarketBuffer(
        times=_put(mesh, buf.times, s2),
        values=_put(mesh, buf.values, s3),
        filled=_put(mesh, buf.filled, s1),
        cursor=_put(mesh, buf.cursor, s1),
    )


def _shard_carry(carry, mesh: Mesh, num_symbols: int):
    """Classify carry leaves by shape: (S, ...) arrays shard over symbols,
    scalars and the (4,) score vectors replicate. Shape-based so future
    carry fields are placed correctly without a manual registry — the
    regime carry AND the incremental indicator carry both route through
    here (every IndicatorCarry leaf is (S,) or (S, k))."""
    # the (4,) market-score vectors must not be mistaken for a symbol axis
    assert num_symbols != 4, "capacity of 4 is ambiguous with score vectors"
    r = _replicated(mesh)

    def place(x):
        x = jnp.asarray(x) if not hasattr(x, "ndim") else x
        is_symbol_axis = x.ndim >= 1 and x.shape[0] == num_symbols
        sh = symbol_sharding(mesh, x.ndim) if is_symbol_axis else r
        return _put(mesh, x, sh)

    return jax.tree_util.tree_map(place, carry)


def shard_engine_state(state: EngineState, mesh: Mesh) -> EngineState:
    """Place the engine state: (S, ...) arrays split over symbols, the
    regime carry's scalars replicated, its per-symbol arrays split."""
    s1 = symbol_sharding(mesh, 1)
    return EngineState(
        buf5=_shard_buffer(state.buf5, mesh),
        buf15=_shard_buffer(state.buf15, mesh),
        regime_carry=_shard_carry(
            state.regime_carry, mesh, state.buf15.capacity
        ),
        mrf_last_emitted=_put(mesh, state.mrf_last_emitted, s1),
        pt_last_signal_close=_put(mesh, state.pt_last_signal_close, s1),
        indicator_carry=_shard_carry(
            state.indicator_carry, mesh, state.buf15.capacity
        ),
    )


def shard_host_inputs(inputs: HostInputs, mesh: Mesh) -> HostInputs:
    """(S,) inputs split over symbols via per-shard slices; scalars
    replicated (one tiny put per device — pod-safe)."""
    s1 = symbol_sharding(mesh, 1)
    r = _replicated(mesh)

    def sym(x):
        return assemble_sharded(mesh, np.asarray(x), s1)

    def rep(x):
        return assemble_sharded(mesh, np.asarray(x), r)

    return HostInputs(
        tracked=sym(inputs.tracked),
        btc_row=rep(inputs.btc_row),
        timestamp_s=rep(inputs.timestamp_s),
        timestamp5_s=rep(inputs.timestamp5_s),
        oi_growth=sym(inputs.oi_growth),
        adp_latest=rep(inputs.adp_latest),
        adp_prev=rep(inputs.adp_prev),
        adp_diff=rep(inputs.adp_diff),
        adp_diff_prev=rep(inputs.adp_diff_prev),
        breadth_momentum_points=rep(inputs.breadth_momentum_points),
        quiet_hours=rep(inputs.quiet_hours),
        grid_policy_allows=rep(inputs.grid_policy_allows),
        is_futures=rep(inputs.is_futures),
        dominance_is_losers=rep(inputs.dominance_is_losers),
        market_domination_reversal=rep(inputs.market_domination_reversal),
    )
