# binquant_tpu — single-container deployment (reference Dockerfile parity:
# one process, heartbeat healthcheck, SIGTERM stop).
FROM python:3.12-slim

WORKDIR /app

COPY pyproject.toml ./
RUN pip install --no-cache-dir \
    "jax[tpu]" flax optax orbax-checkpoint chex einops \
    numpy pandas pydantic httpx websockets pytest pytest-asyncio

COPY binquant_tpu ./binquant_tpu
COPY main.py healthcheck.py bench.py __graft_entry__.py ./

HEALTHCHECK --interval=60s --timeout=10s --retries=3 \
    CMD ["python", "healthcheck.py"]

STOPSIGNAL SIGTERM
CMD ["python", "main.py"]
